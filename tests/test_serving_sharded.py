"""Multi-chip serving (ISSUE 11): mesh-sharded bucket programs, replica
dispatch, tree/FM serving kernels, fallback observability.

The load-bearing invariants:
  * a feature-sharded model serves BITWISE-identically on a 1-, 4- and
    8-device mesh (the lane-blocked reduction contract of
    serving/sharded.py), dense AND sparse;
  * model weights land straight in their mesh placement (P('d') on the
    feature axis — the io/sharding.py rules) on construction and on
    every hot swap, with no torn responses under swap load;
  * serving traffic is visible to the collective manifest (one psum per
    sharded dispatch, replayed per invocation);
  * tree and FM mappers serve through CompiledPredictor with exact-label
    parity vs their host mappers (trees: bitwise including details);
  * every host-path fallback is recorded (metric + one RuntimeWarning),
    never silent.
"""

import threading
import warnings

import numpy as np
import pytest

from alink_tpu.common.metrics import MetricsRegistry, set_registry
from alink_tpu.common.mtable import MTable
from alink_tpu.common.params import Params
from alink_tpu.common.vector import DenseVector, SparseVector
from alink_tpu.operator.batch.classification.linear import (
    LogisticRegressionTrainBatchOp, SoftmaxTrainBatchOp)
from alink_tpu.operator.batch.source.sources import MemSourceBatchOp
from alink_tpu.operator.common.linear.mapper import LinearModelMapper
from alink_tpu.serving import CompiledPredictor, PredictServer
from alink_tpu.serving.predictor import (_reset_fallback_warnings,
                                         record_serve_fallback)
from alink_tpu.serving.sharded import (SERVE_LANES, mesh_fingerprint,
                                       serve_replicas,
                                       serve_sharded_enabled, serving_mesh)


def _tables_equal(a: MTable, b: MTable) -> bool:
    if a.col_names != b.col_names or a.num_rows != b.num_rows:
        return False
    return all(str(x) == str(y)
               for c in a.col_names for x, y in zip(a.col(c), b.col(c)))


def _dense_fixture(seed=0, n=96, d=20, max_iter=3, detail=True):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d)
    y = (X @ rng.randn(d) > 0).astype(np.int64)
    vecs = np.empty(n, object)
    vecs[:] = [DenseVector(X[i]) for i in range(n)]
    tbl = MTable({"vec": vecs, "label": y}, "vec VECTOR, label LONG")
    warm = LogisticRegressionTrainBatchOp(
        vector_col="vec", label_col="label",
        max_iter=max_iter).link_from(MemSourceBatchOp(tbl))
    pp = {"prediction_col": "pred", "vector_col": "vec"}
    if detail:
        pp["prediction_detail_col"] = "det"
    schema = tbl.select(["vec"]).schema
    mapper = LinearModelMapper(warm.get_output_table().schema, schema,
                               Params(pp))
    mapper.load_model(warm.get_output_table())
    return tbl, warm, mapper, schema


@pytest.fixture(scope="module")
def dense():
    tbl, warm, mapper, schema = _dense_fixture()
    return {"tbl": tbl, "warm": warm, "mapper": mapper, "schema": schema}


def _mesh(n):
    import jax
    return serving_mesh(jax.devices()[:n])


class TestShardedParity:
    """Bitwise parity of sharded vs single-device bucket programs."""

    def test_dense_mesh_1_4_8_bitwise(self, dense):
        req = dense["tbl"].select(["vec"]).first_n(13)
        outs = {}
        for s in (1, 4, 8):
            pred = CompiledPredictor(dense["mapper"], buckets=(4, 16),
                                     sharded=True, mesh=_mesh(s))
            outs[s] = pred.predict_table(req)
        assert _tables_equal(outs[1], outs[4])
        assert _tables_equal(outs[1], outs[8])
        host = dense["mapper"].map_table(req)
        assert list(outs[4].col("pred")) == list(host.col("pred"))

    def test_sparse_mesh_1_vs_4_bitwise(self):
        rng = np.random.RandomState(3)
        n, dim, nnz = 80, 512, 10
        rows = []
        for _ in range(n):
            idx = np.sort(rng.choice(dim, nnz, replace=False))
            rows.append(SparseVector(dim, idx, rng.randn(nnz)))
        vc = np.empty(n, object)
        vc[:] = rows
        y = np.asarray([1 if sum(v.values) > 0 else 0 for v in rows])
        tbl = MTable({"vec": vc, "label": y}, "vec VECTOR, label LONG")
        warm = LogisticRegressionTrainBatchOp(
            vector_col="vec", label_col="label",
            max_iter=2).link_from(MemSourceBatchOp(tbl))
        mapper = LinearModelMapper(
            warm.get_output_table().schema, tbl.select(["vec"]).schema,
            Params({"prediction_col": "pred", "vector_col": "vec"}))
        mapper.load_model(warm.get_output_table())
        req = tbl.select(["vec"])
        o1 = CompiledPredictor(mapper, buckets=(16, 128), sharded=True,
                               mesh=_mesh(1)).predict_table(req)
        o4 = CompiledPredictor(mapper, buckets=(16, 128), sharded=True,
                               mesh=_mesh(4)).predict_table(req)
        assert _tables_equal(o1, o4)
        assert list(o4.col("pred")) == \
            list(mapper.map_table(req).col("pred"))

    def test_bucket_padding_still_bitwise_noop_sharded(self, dense):
        pred = CompiledPredictor(dense["mapper"], buckets=(1, 4, 16),
                                 sharded=True, mesh=_mesh(4))
        req = dense["tbl"].select(["vec"]).first_n(3)
        batched = pred.predict_table(req)
        for i in range(3):
            assert tuple(map(str, batched.row(i))) == \
                tuple(map(str, pred.predict_row(req.row(i))))

    def test_model_lands_in_mesh_placement(self, dense):
        """The weight vector must be feature-sharded P('d') across the
        mesh devices — straight from the host table, no replicated
        staging copy."""
        from jax.sharding import PartitionSpec as P
        mesh = _mesh(4)
        pred = CompiledPredictor(dense["mapper"], buckets=(4,),
                                 sharded=True, mesh=mesh)
        w = pred._active.device_arrays[0]
        assert w.sharding.spec == P("d")
        assert len(w.sharding.device_set) == 4

    def test_program_key_carries_mesh_fingerprint(self, dense):
        pred4 = CompiledPredictor(dense["mapper"], buckets=(4,),
                                  sharded=True, mesh=_mesh(4))
        pred4.predict_table(dense["tbl"].select(["vec"]).first_n(2))
        (key,) = pred4._programs
        assert key[-1] == mesh_fingerprint(_mesh(4))
        pred_un = CompiledPredictor(dense["mapper"], buckets=(4,),
                                    sharded=False)
        pred_un.predict_table(dense["tbl"].select(["vec"]).first_n(2))
        (ukey,) = pred_un._programs
        assert ukey[-1] is None and ukey[:-1] == key[:-1]

    def test_sharded_dispatch_records_collectives(self, dense):
        reg = MetricsRegistry()
        old = set_registry(reg)
        try:
            pred = CompiledPredictor(dense["mapper"], buckets=(4,),
                                     sharded=True, mesh=_mesh(4))
            req = dense["tbl"].select(["vec"]).first_n(4)
            for _ in range(3):
                pred.predict_table(req)
            calls = reg.value("alink_collective_calls_total",
                              {"collective": "AllReduce"})
            # one psum per dispatch, replayed per invocation (>= 3; the
            # AOT capture itself records into the manifest, not here)
            assert calls >= 3
        finally:
            set_registry(old)


class TestShardedSwap:
    def test_swap_model_stays_in_placement_and_compiles_nothing(
            self, dense):
        from jax.sharding import PartitionSpec as P
        pred = CompiledPredictor(dense["mapper"], buckets=(4, 16),
                                 sharded=True, mesh=_mesh(4))
        req = dense["tbl"].select(["vec"]).first_n(10)
        pred.predict_table(req)
        progs = pred.cache_stats()["programs"]
        _t2, warm2, _m2, _s2 = _dense_fixture(seed=11, max_iter=2)
        pred.swap_model(warm2.get_output_table())
        assert pred._active.device_arrays[0].sharding.spec == P("d")
        pred.predict_table(req)
        assert pred.cache_stats()["programs"] == progs

    def test_swap_weights_in_place(self, dense):
        """The no-gather path: device-resident same-geometry arrays
        install as a new version without a model-table reload."""
        import jax
        pred = CompiledPredictor(dense["mapper"], buckets=(4,),
                                 sharded=True, mesh=_mesh(4))
        req = dense["tbl"].select(["vec"]).first_n(4)
        before = pred.predict_table(req)
        w, b = pred._active.device_arrays
        v = pred.swap_weights((jax.numpy.asarray(w) * 2.0, b))
        assert v == 2 and pred.model_version == 2
        after = pred.predict_table(req)
        assert list(before.col("det")) != list(after.col("det"))
        # same geometry: no new program
        assert pred.cache_stats()["programs"] == 1

    def test_swap_weights_refuses_geometry_change(self, dense):
        pred = CompiledPredictor(dense["mapper"], buckets=(4,),
                                 sharded=True, mesh=_mesh(4))
        w, b = pred._active.kernel.model_arrays
        with pytest.raises(ValueError, match="geometry"):
            pred.swap_weights((np.zeros(w.shape[0] * 2), b))
        with pytest.raises(ValueError, match="arrays"):
            pred.swap_weights((w,))

    def test_no_torn_responses_under_sharded_swap_load(self, dense):
        """Serve continuously on the 4-device mesh while another thread
        swaps between two feature-sharded models; every response must
        match one of the two models' outputs exactly."""
        _t2, warm2, _m2, _s2 = _dense_fixture(seed=13, max_iter=2)
        m_a = dense["warm"].get_output_table()
        m_b = warm2.get_output_table()
        pred = CompiledPredictor(dense["mapper"], buckets=(1, 4),
                                 sharded=True, mesh=_mesh(4))
        probe = dense["tbl"].select(["vec"]).row(0)
        expected = set()
        for mt in (m_a, m_b):
            fm = LinearModelMapper(mt.schema, dense["schema"],
                                   dense["mapper"].params)
            fm.load_model(mt)
            expected.add(str(CompiledPredictor(
                fm, buckets=(1, 4), sharded=True,
                mesh=_mesh(4)).predict_row(probe)))
        stop = threading.Event()

        def swapper():
            i = 0
            while not stop.is_set():
                pred.swap_model(m_b if i % 2 == 0 else m_a)
                i += 1
        th = threading.Thread(target=swapper, daemon=True)
        th.start()
        observed = set()
        for _ in range(120):
            observed.add(str(pred.predict_row(probe)))
        stop.set()
        th.join(10)
        assert observed <= expected and len(observed) == 2


class TestSwapFallbacks:
    def test_swap_unshardable_kernel_serves_single_device(self, dense):
        """Swapping a model whose kernel cannot shard (softmax) into a
        SHARDED predictor must keep serving (single-device programs for
        that version, fallback recorded) — not crash every dispatch."""
        pred = CompiledPredictor(dense["mapper"], buckets=(4, 16),
                                 sharded=True, mesh=_mesh(4))
        req = dense["tbl"].select(["vec"]).first_n(6)
        pred.predict_table(req)
        rng = np.random.RandomState(0)
        n, d, k = 60, 20, 3
        X = rng.randn(n, d)
        y = rng.randint(0, k, n)
        vecs = np.empty(n, object)
        vecs[:] = [DenseVector(X[i]) for i in range(n)]
        t = MTable({"vec": vecs, "label": y}, "vec VECTOR, label LONG")
        warm = SoftmaxTrainBatchOp(
            vector_col="vec", label_col="label",
            max_iter=2).link_from(MemSourceBatchOp(t))
        _reset_fallback_warnings()
        with pytest.warns(RuntimeWarning, match="no-sharded-kernel"):
            pred.swap_model(warm.get_output_table())
        out = pred.predict_table(req)          # must not raise
        assert out.num_rows == 6
        # the fallback version's programs are keyed WITHOUT the mesh
        # (single-device), distinct from the sharded ones
        assert any(key[-1] is None for key in pred._programs)
        _reset_fallback_warnings()

    def test_sync_swap_blocks_all_replica_placements(self, dense,
                                                     monkeypatch):
        monkeypatch.setenv("ALINK_TPU_SERVE_SWAP", "sync")
        _t2, warm2, _m2, _s2 = _dense_fixture(seed=31, max_iter=2)
        pred = CompiledPredictor(dense["mapper"], buckets=(1, 4))
        srv = PredictServer(pred, replicas=4, name="sync_reps")
        try:
            srv.swap_model(warm2.get_output_table())
            import jax
            for i in range(4):
                for a in pred._active.arrays_for(i):
                    assert isinstance(a, jax.Array)
            row = dense["tbl"].select(["vec"]).row(0)
            for _ in range(8):
                assert srv.predict(row, timeout=30) is not None
        finally:
            srv.close()


class TestReplicaDispatch:
    def test_replicas_serve_correct_results(self, dense):
        pred = CompiledPredictor(dense["mapper"], buckets=(1, 4))
        srv = PredictServer(pred, replicas=4, name="reps4")
        try:
            assert srv.replicas == 4
            assert len(set(pred.replica_devices)) == 4
            rows = [dense["tbl"].select(["vec"]).row(i) for i in range(8)]
            want = [str(pred.predict_row(r)) for r in rows]
            futs = [(j, srv.submit(rows[j]))
                    for _ in range(6) for j in range(8)]
            for j, f in futs:
                assert str(f.result(30)) == want[j]
            assert srv.stats()["requests"] >= 48
        finally:
            srv.close()

    def test_auto_replicas_span_session_mesh(self, dense):
        pred = CompiledPredictor(dense["mapper"], buckets=(1, 4))
        srv = PredictServer(pred, replicas=0, name="reps_auto")
        try:
            assert srv.replicas == 8      # the 8-device test mesh
        finally:
            srv.close()

    def test_swap_reaches_every_replica(self, dense):
        _t2, warm2, _m2, _s2 = _dense_fixture(seed=17, max_iter=2)
        pred = CompiledPredictor(dense["mapper"], buckets=(1, 4))
        srv = PredictServer(pred, replicas=4, name="reps_swap")
        try:
            srv.swap_model(warm2.get_output_table())
            fresh = LinearModelMapper(
                warm2.get_output_table().schema, dense["schema"],
                dense["mapper"].params)
            fresh.load_model(warm2.get_output_table())
            want = str(CompiledPredictor(fresh, buckets=(1, 4)).predict_row(
                dense["tbl"].select(["vec"]).row(0)))
            row = dense["tbl"].select(["vec"]).row(0)
            for _ in range(24):           # hits every replica w.h.p.
                assert str(srv.predict(row, timeout=30)) == want
        finally:
            srv.close()

    def test_sharded_predictor_forces_one_replica(self, dense):
        pred = CompiledPredictor(dense["mapper"], buckets=(1, 4),
                                 sharded=True, mesh=_mesh(4))
        srv = PredictServer(pred, replicas=4, name="reps_sharded")
        try:
            assert srv.replicas == 1
        finally:
            srv.close()

    def test_replica_devices_do_not_compose_with_sharded(self, dense):
        import jax
        with pytest.raises(ValueError, match="replica_devices"):
            CompiledPredictor(dense["mapper"], buckets=(4,), sharded=True,
                              mesh=_mesh(4),
                              replica_devices=jax.devices()[:2])


class TestTreeServingKernels:
    @pytest.fixture(scope="class")
    def tree_data(self):
        rng = np.random.RandomState(0)
        n = 160
        return MTable(
            {"a": rng.randn(n), "b": rng.randn(n), "c": rng.randn(n),
             "cat": np.asarray([["x", "y", "z"][i % 3]
                                for i in range(n)], object),
             "label": (rng.randn(n) > 0).astype(np.int64)},
            "a DOUBLE, b DOUBLE, c DOUBLE, cat STRING, label LONG")

    def _check(self, warm, tree_data, detail=True):
        from alink_tpu.operator.batch.classification.tree_ops import (
            TreeModelMapper)
        pp = {"prediction_col": "pred"}
        if detail:
            pp["prediction_detail_col"] = "det"
        mapper = TreeModelMapper(
            warm.get_output_table().schema,
            tree_data.select(["a", "b", "c", "cat"]).schema, Params(pp))
        mapper.load_model(warm.get_output_table())
        req = tree_data.select(["a", "b", "c", "cat"])
        pred = CompiledPredictor(mapper, buckets=(4, 32, 256))
        got, ref = pred.predict_table(req), mapper.map_table(req)
        # BITWISE on the f64 test mesh: the device traversal + host-order
        # leaf accumulation reproduce the numpy mapper exactly
        assert _tables_equal(got, ref)
        # bucket padding stays a bitwise no-op
        r3 = pred.predict_table(req.first_n(3))
        for i in range(3):
            assert tuple(map(str, r3.row(i))) == \
                tuple(map(str, pred.predict_row(req.row(i))))
        return pred

    def test_gbdt_classifier_bitwise(self, tree_data):
        from alink_tpu.operator.batch.classification.tree_ops import (
            GbdtTrainBatchOp)
        warm = GbdtTrainBatchOp(
            feature_cols=["a", "b", "c"], label_col="label", num_trees=6,
            max_depth=3).link_from(MemSourceBatchOp(tree_data))
        self._check(warm, tree_data)

    def test_gbdt_categorical_bitwise_incl_oov(self, tree_data):
        from alink_tpu.operator.batch.classification.tree_ops import (
            GbdtTrainBatchOp, TreeModelMapper)
        warm = GbdtTrainBatchOp(
            feature_cols=["a", "b", "c", "cat"], categorical_cols=["cat"],
            label_col="label", num_trees=4,
            max_depth=3).link_from(MemSourceBatchOp(tree_data))
        pred = self._check(warm, tree_data)
        # out-of-vocabulary category routes right, identically to host
        oov = MTable({"a": np.asarray([0.1]), "b": np.asarray([0.2]),
                      "c": np.asarray([-0.3]),
                      "cat": np.asarray(["NEVER-SEEN"], object)},
                     "a DOUBLE, b DOUBLE, c DOUBLE, cat STRING")
        assert _tables_equal(pred.predict_table(oov),
                             pred.host_reference(oov))

    def test_gbdt_regression_bitwise(self):
        from alink_tpu.operator.batch.classification.tree_ops import (
            GbdtRegTrainBatchOp, TreeModelMapper)
        rng = np.random.RandomState(5)
        n = 120
        t = MTable({"a": rng.randn(n), "b": rng.randn(n),
                    "label": rng.randn(n)},
                   "a DOUBLE, b DOUBLE, label DOUBLE")
        warm = GbdtRegTrainBatchOp(
            feature_cols=["a", "b"], label_col="label", num_trees=5,
            max_depth=3).link_from(MemSourceBatchOp(t))
        mapper = TreeModelMapper(warm.get_output_table().schema,
                                 t.select(["a", "b"]).schema,
                                 Params({"prediction_col": "pred"}))
        mapper.load_model(warm.get_output_table())
        req = t.select(["a", "b"])
        pred = CompiledPredictor(mapper, buckets=(8, 128))
        assert _tables_equal(pred.predict_table(req),
                             mapper.map_table(req))

    def test_vector_model_narrow_batch_pads_to_split_width(self):
        """A vector-input tree model whose splits address feature j must
        serve batches of NARROWER vectors (absent entries read 0) —
        identically on the host and device paths, independent of
        batch-mates (the encode pins the width to the model's needs)."""
        from alink_tpu.common.vector import DenseVector as DV
        from alink_tpu.operator.batch.classification.tree_ops import (
            GbdtTrainBatchOp, TreeModelMapper)
        rng = np.random.RandomState(8)
        n, d = 120, 6
        X = rng.randn(n, d)
        y = (X[:, 5] > 0).astype(np.int64)     # split lives at index 5
        vecs = np.empty(n, object)
        vecs[:] = [DV(X[i]) for i in range(n)]
        t = MTable({"vec": vecs, "label": y}, "vec VECTOR, label LONG")
        warm = GbdtTrainBatchOp(
            vector_col="vec", label_col="label", num_trees=3,
            max_depth=2).link_from(MemSourceBatchOp(t))
        mapper = TreeModelMapper(warm.get_output_table().schema,
                                 t.select(["vec"]).schema,
                                 Params({"prediction_col": "pred"}))
        mapper.load_model(warm.get_output_table())
        assert mapper._model_width() == 6
        narrow = np.empty(4, object)
        narrow[:] = [SparseVector(3, [0, 2], [0.5, -0.5])
                     for _ in range(4)]
        req = MTable({"vec": narrow}, "vec VECTOR")
        pred = CompiledPredictor(mapper, buckets=(4, 16))
        got = pred.predict_table(req)
        ref = mapper.map_table(req)            # host path, same widening
        assert _tables_equal(got, ref)

    def test_random_forest_and_decision_tree_bitwise(self, tree_data):
        from alink_tpu.operator.batch.classification.tree_ops import (
            DecisionTreeTrainBatchOp, RandomForestTrainBatchOp)
        rf = RandomForestTrainBatchOp(
            feature_cols=["a", "b", "c"], label_col="label", num_trees=5,
            max_depth=3, seed=3).link_from(MemSourceBatchOp(tree_data))
        self._check(rf, tree_data)
        dt = DecisionTreeTrainBatchOp(
            feature_cols=["a", "b", "c"], label_col="label",
            max_depth=4).link_from(MemSourceBatchOp(tree_data))
        self._check(dt, tree_data)

    def test_tree_same_geometry_swap_compiles_nothing(self, tree_data):
        from alink_tpu.operator.batch.classification.tree_ops import (
            GbdtTrainBatchOp, TreeModelMapper)
        warm = GbdtTrainBatchOp(
            feature_cols=["a", "b", "c"], label_col="label", num_trees=4,
            max_depth=3, seed=1).link_from(MemSourceBatchOp(tree_data))
        mapper = TreeModelMapper(
            warm.get_output_table().schema,
            tree_data.select(["a", "b", "c", "cat"]).schema,
            Params({"prediction_col": "pred"}))
        mapper.load_model(warm.get_output_table())
        pred = CompiledPredictor(mapper, buckets=(32,))
        req = tree_data.select(["a", "b", "c", "cat"]).first_n(20)
        pred.predict_table(req)
        progs = pred.cache_stats()["programs"]
        warm2 = GbdtTrainBatchOp(
            feature_cols=["a", "b", "c"], label_col="label", num_trees=4,
            max_depth=3, seed=9).link_from(MemSourceBatchOp(tree_data))
        pred.swap_model(warm2.get_output_table())
        pred.predict_table(req)
        assert pred.cache_stats()["programs"] == progs


class TestFmServingKernel:
    def test_fm_classifier_dense_labels_exact(self):
        import json
        from alink_tpu.operator.batch.classification.fm_ops import (
            FmClassifierTrainBatchOp, FmModelMapper)
        rng = np.random.RandomState(1)
        n, d = 150, 24
        X = rng.randn(n, d)
        y = (X @ rng.randn(d) > 0).astype(np.int64)
        vecs = np.empty(n, object)
        vecs[:] = [DenseVector(X[i]) for i in range(n)]
        t = MTable({"vec": vecs, "label": y}, "vec VECTOR, label LONG")
        warm = FmClassifierTrainBatchOp(
            vector_col="vec", label_col="label", num_epochs=3,
            num_factor=5).link_from(MemSourceBatchOp(t))
        mapper = FmModelMapper(
            warm.get_output_table().schema, t.select(["vec"]).schema,
            Params({"prediction_col": "pred",
                    "prediction_detail_col": "det", "vector_col": "vec"}))
        mapper.load_model(warm.get_output_table())
        req = t.select(["vec"])
        pred = CompiledPredictor(mapper, buckets=(8, 64, 256))
        got, ref = pred.predict_table(req), mapper.map_table(req)
        assert list(got.col("pred")) == list(ref.col("pred"))
        for a, b in zip(got.col("det"), ref.col("det")):
            pa, pb = json.loads(str(a)), json.loads(str(b))
            assert pa.keys() == pb.keys()
            assert all(abs(pa[kk] - pb[kk]) < 1e-10 for kk in pa)
        # bucket padding bitwise no-op
        r3 = pred.predict_table(req.first_n(3))
        for i in range(3):
            assert tuple(map(str, r3.row(i))) == \
                tuple(map(str, pred.predict_row(req.row(i))))

    def test_fm_regressor_sparse_margins_close(self):
        from alink_tpu.operator.batch.classification.fm_ops import (
            FmRegressorTrainBatchOp, FmModelMapper)
        rng = np.random.RandomState(2)
        n, dim, nnz = 100, 64, 6
        rows = []
        for _ in range(n):
            idx = np.sort(rng.choice(dim, nnz, replace=False))
            rows.append(SparseVector(dim, idx, rng.randn(nnz)))
        vc = np.empty(n, object)
        vc[:] = rows
        t = MTable({"vec": vc, "label": rng.randn(n)},
                   "vec VECTOR, label DOUBLE")
        warm = FmRegressorTrainBatchOp(
            vector_col="vec", label_col="label", num_epochs=2,
            num_factor=4).link_from(MemSourceBatchOp(t))
        mapper = FmModelMapper(
            warm.get_output_table().schema, t.select(["vec"]).schema,
            Params({"prediction_col": "pred", "vector_col": "vec"}))
        mapper.load_model(warm.get_output_table())
        req = t.select(["vec"])
        pred = CompiledPredictor(mapper, buckets=(16, 128))
        got = np.asarray(pred.predict_table(req).col("pred"), float)
        ref = np.asarray(mapper.map_table(req).col("pred"), float)
        np.testing.assert_allclose(got, ref, rtol=1e-10, atol=1e-12)


class TestStreamTwinWidening:
    def _drain(self, op):
        outs = list(op.micro_batches())
        merged = outs[0]
        for mt in outs[1:]:
            merged = merged.concat_rows(mt)
        return merged

    def test_gbdt_twin_rides_compiled_path(self, monkeypatch):
        from alink_tpu.operator.batch.classification.tree_ops import (
            GbdtTrainBatchOp)
        from alink_tpu.operator.stream.predict_ops import (
            GbdtPredictStreamOp)
        from alink_tpu.operator.stream.source.sources import (
            MemSourceStreamOp)
        rng = np.random.RandomState(0)
        n = 80
        t = MTable({"a": rng.randn(n), "b": rng.randn(n),
                    "label": (rng.randn(n) > 0).astype(np.int64)},
                   "a DOUBLE, b DOUBLE, label LONG")
        warm = GbdtTrainBatchOp(
            feature_cols=["a", "b"], label_col="label", num_trees=4,
            max_depth=3).link_from(MemSourceBatchOp(t))

        def run():
            src = MemSourceStreamOp(t.select(["a", "b"]), batch_size=32)
            return self._drain(GbdtPredictStreamOp(
                warm, prediction_col="pred",
                prediction_detail_col="det").link_from(src))
        monkeypatch.delenv("ALINK_TPU_SERVE_COMPILED", raising=False)
        off = run()
        monkeypatch.setenv("ALINK_TPU_SERVE_COMPILED", "1")
        on = run()
        assert _tables_equal(on, off)     # trees are bitwise on f64

    def test_fm_twin_rides_compiled_path(self, monkeypatch):
        from alink_tpu.operator.batch.classification.fm_ops import (
            FmClassifierTrainBatchOp)
        from alink_tpu.operator.stream.predict_ops import (
            FmPredictStreamOp)
        from alink_tpu.operator.stream.source.sources import (
            MemSourceStreamOp)
        rng = np.random.RandomState(4)
        n, d = 90, 16
        X = rng.randn(n, d)
        y = (X @ rng.randn(d) > 0).astype(np.int64)
        vecs = np.empty(n, object)
        vecs[:] = [DenseVector(X[i]) for i in range(n)]
        t = MTable({"vec": vecs, "label": y}, "vec VECTOR, label LONG")
        warm = FmClassifierTrainBatchOp(
            vector_col="vec", label_col="label", num_epochs=2,
            num_factor=4).link_from(MemSourceBatchOp(t))

        def run():
            src = MemSourceStreamOp(t.select(["vec"]), batch_size=32)
            return self._drain(FmPredictStreamOp(
                warm, prediction_col="pred").link_from(src))
        monkeypatch.delenv("ALINK_TPU_SERVE_COMPILED", raising=False)
        off = run()
        monkeypatch.setenv("ALINK_TPU_SERVE_COMPILED", "1")
        on = run()
        assert list(on.col("pred")) == list(off.col("pred"))


class TestFallbackObservability:
    def test_metric_and_once_warning(self, dense):
        from alink_tpu.mapper.base import ModelMapper

        class NoKernel2(ModelMapper):
            def load_model(self, t):
                pass
        reg = MetricsRegistry()
        old = set_registry(reg)
        _reset_fallback_warnings()
        try:
            m = NoKernel2(dense["tbl"].schema, dense["schema"])
            with pytest.warns(RuntimeWarning, match="no-serving-kernel"):
                assert CompiledPredictor.for_mapper(m) is None
            with warnings.catch_warnings():
                warnings.simplefilter("error")   # second time: NO warning
                assert CompiledPredictor.for_mapper(m) is None
            assert reg.value("alink_serve_fallback_total",
                             {"mapper": "NoKernel2",
                              "reason": "no-serving-kernel"}) == 2
        finally:
            set_registry(old)
            _reset_fallback_warnings()

    def test_sharded_fallback_reasons_recorded(self, dense):
        reg = MetricsRegistry()
        old = set_registry(reg)
        _reset_fallback_warnings()
        try:
            # softmax kernel cannot shard -> recorded, serves unsharded
            rng = np.random.RandomState(0)
            n, d, k = 60, 8, 3
            X = rng.randn(n, d)
            y = rng.randint(0, k, n)
            vecs = np.empty(n, object)
            vecs[:] = [DenseVector(X[i]) for i in range(n)]
            t = MTable({"vec": vecs, "label": y},
                       "vec VECTOR, label LONG")
            warm = SoftmaxTrainBatchOp(
                vector_col="vec", label_col="label",
                max_iter=2).link_from(MemSourceBatchOp(t))
            sm = LinearModelMapper(
                warm.get_output_table().schema, t.select(["vec"]).schema,
                Params({"prediction_col": "pred", "vector_col": "vec"}))
            sm.load_model(warm.get_output_table())
            with pytest.warns(RuntimeWarning, match="no-sharded-kernel"):
                pred = CompiledPredictor(sm, buckets=(4,), sharded=True,
                                         mesh=_mesh(4))
            assert not pred.sharded
            assert pred.predict_table(t.select(["vec"]).first_n(3)
                                      ).num_rows == 3
            # a mesh whose size does not divide the lane count
            _reset_fallback_warnings()
            with pytest.warns(RuntimeWarning, match="mesh-indivisible"):
                pred3 = CompiledPredictor(dense["mapper"], buckets=(4,),
                                          sharded=True, mesh=_mesh(3))
            assert not pred3.sharded
        finally:
            set_registry(old)
            _reset_fallback_warnings()

    def test_geometry_refusal_falls_back_in_stream_twin(self, dense,
                                                        monkeypatch):
        """A kernel refusing a request geometry must not kill the stream
        under ALINK_TPU_SERVE_COMPILED: the twin records the fallback
        (warning + metric) and serves the batch through the host
        mapper."""
        from alink_tpu.operator.stream.predict_ops import (
            LogisticRegressionPredictStreamOp)
        from alink_tpu.operator.stream.source.sources import (
            MemSourceStreamOp)
        monkeypatch.setenv("ALINK_TPU_SERVE_COMPILED", "1")
        monkeypatch.setattr(
            CompiledPredictor, "predict_table",
            lambda self, data, replica=0: (_ for _ in ()).throw(
                ValueError("kernel refuses this geometry")))
        _reset_fallback_warnings()
        reg = MetricsRegistry()
        old = set_registry(reg)
        try:
            src = MemSourceStreamOp(dense["tbl"].select(["vec"]),
                                    batch_size=32)
            op = LogisticRegressionPredictStreamOp(
                dense["warm"], prediction_col="pred",
                prediction_detail_col="det",
                vector_col="vec").link_from(src)
            with pytest.warns(RuntimeWarning, match="geometry-refused"):
                outs = list(op.micro_batches())
            assert sum(mt.num_rows for mt in outs) == \
                dense["tbl"].num_rows
            # host-path output; the fallback counts PER refused batch
            # (96 rows / batch_size 32 = 3) under the STABLE reason
            # label — request-specific text stays out of the metric
            assert reg.value("alink_serve_fallback_total",
                             {"mapper": "LinearModelMapper",
                              "reason": "geometry-refused"}) == 3
        finally:
            set_registry(old)
            _reset_fallback_warnings()


class TestDoctorAndHistory:
    ROW = {"samples_per_sec_per_chip": 5200.0, "qps_per_chip": 5200.0,
           "parity": "bitwise", "torn_responses": 0,
           "failed_requests": 0, "model_swaps": 24,
           "qps_1dev": 6100.0, "qps_per_chip_1dev": 6100.0,
           "p99_ms_1dev": 4.1,
           "qps_4dev": 22800.0, "qps_per_chip_4dev": 5700.0,
           "p99_ms_4dev": 4.4,
           "qps_8dev": 41600.0, "qps_per_chip_8dev": 5200.0,
           "p99_ms_8dev": 4.9, "per_chip_scaling": 0.852,
           "bound": "serving-host"}

    def test_doctor_per_chip_qps_verdict_line(self):
        import tools.doctor as doctor
        bench = {"workloads": {"serve_logreg_sharded": dict(self.ROW)},
                 "rig": {"dispatch_gap_est_s": 1e-4}}
        doc = doctor.diagnose(bench, None, None, 100.0, 800.0)
        (v,) = doc["serving"]
        assert v["qps_per_chip_by_devices"] == {
            "1": 6100.0, "4": 5700.0, "8": 5200.0}
        assert v["per_chip_scaling"] == 0.852
        text = doctor.render(doc)
        assert "QPS/chip at 1/4/8 devices: 6,100 -> 5,700 -> 5,200" \
            in text
        assert "verdict: healthy" in text

    def test_doctor_flags_decaying_per_chip_and_parity(self):
        import tools.doctor as doctor
        row = dict(self.ROW, qps_per_chip_8dev=1200.0, parity="MISMATCH")
        bench = {"workloads": {"serve_logreg_sharded": row}, "rig": {}}
        doc = doctor.diagnose(bench, None, None, 100.0, 800.0)
        fixes = "\n".join(doc["serving"][0]["fixes"])
        assert "QPS/chip decays" in fixes
        assert "NOT bitwise-identical across mesh sizes" in fixes

    def test_bench_history_labels_sharded_row(self, tmp_path):
        import json as _json

        import tools.bench_history as bh
        r1 = {"metric": "m", "value": 1.0, "baseline_fp": "fp1",
              "workloads_sps_vs": {
                  "serve_logreg_sharded": [5200.0, 0, 0],
                  "serve_logreg": [9000.0, 0, 0]}}
        p1 = tmp_path / "BENCH_r01.json"
        p1.write_text(_json.dumps(r1))
        hist = bh.build_history([str(p1)])
        text = bh.render(hist, [])
        assert "serve_logreg_sharded (qps/chip)" in text
        assert "serve_logreg (qps)" in text


class TestShardedFlags:
    def test_flags_registered_with_justification(self):
        from alink_tpu.common.flags import FLAGS
        for name in ("ALINK_TPU_SERVE_SHARDED", "ALINK_TPU_SERVE_REPLICAS"):
            flag = FLAGS.get(name)
            assert flag is not None
            assert flag.key_neutral    # justified, not silent
        assert FLAGS.get("ALINK_TPU_SERVE_REPLICAS").read() == 1

    def test_accessors_parse(self, monkeypatch):
        monkeypatch.delenv("ALINK_TPU_SERVE_SHARDED", raising=False)
        assert serve_sharded_enabled() is False
        monkeypatch.setenv("ALINK_TPU_SERVE_SHARDED", "1")
        assert serve_sharded_enabled() is True
        monkeypatch.setenv("ALINK_TPU_SERVE_REPLICAS", "-3")
        assert serve_replicas() == 0      # clamped to the auto sentinel
        monkeypatch.setenv("ALINK_TPU_SERVE_REPLICAS", "4")
        assert serve_replicas() == 4

    def test_flag_routes_predictor_to_sharded(self, dense, monkeypatch):
        monkeypatch.setenv("ALINK_TPU_SERVE_SHARDED", "1")
        pred = CompiledPredictor(dense["mapper"], buckets=(4,))
        assert pred.sharded and pred.mesh is not None
        assert int(pred.mesh.devices.size) == 8   # the session mesh
        monkeypatch.delenv("ALINK_TPU_SERVE_SHARDED")
        assert not CompiledPredictor(dense["mapper"], buckets=(4,)).sharded

    def test_lane_count_divisible_meshes(self):
        assert SERVE_LANES % 8 == 0 and SERVE_LANES % 4 == 0
