"""Mapper / model-serving layer.

Re-design of the reference mapper stack (common/mapper/Mapper.java,
ModelMapper + ModelMapperAdapter.java:36-45, OutputColsHelper).

TPU-first change: the primary interface is **batched** —
``map_table(MTable) -> MTable`` — so model application can jit one device
kernel over the whole batch instead of the reference's per-row ``map(Row)``
(ModelMapperAdapter.java:42-45). A per-row ``map_row`` remains for
LocalPredictor-style embedded serving and defaults to a 1-row table trip.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..common.mtable import MTable
from ..common.params import Params, WithParams
from ..common.types import TableSchema


class OutputColsHelper:
    """Merge reserved input columns with mapper output columns.

    reference: common/utils/OutputColsHelper.java — output schema =
    reserved cols (default: all input cols) + appended/overwritten
    output cols.
    """

    def __init__(self, data_schema: TableSchema, output_cols: Sequence[str],
                 output_types: Sequence[str], reserved_cols: Optional[Sequence[str]] = None):
        self.data_schema = data_schema
        self.output_cols = list(output_cols)
        self.output_types = list(output_types)
        if reserved_cols is None:
            reserved_cols = [c for c in data_schema.names]
        self.reserved_cols = [c for c in reserved_cols if c not in set(self.output_cols)]

    def get_output_schema(self) -> TableSchema:
        names = self.reserved_cols + self.output_cols
        types = ([self.data_schema.type_of(c) for c in self.reserved_cols]
                 + self.output_types)
        return TableSchema(names, types)

    def build_output(self, data: MTable, out_columns: Sequence[Any]) -> MTable:
        cols = {c: data.col(c) for c in self.reserved_cols}
        for name, values in zip(self.output_cols, out_columns):
            cols[name] = values
        return MTable(cols, self.get_output_schema())


class Mapper(WithParams):
    """Stateless row/batch transformer (reference common/mapper/Mapper.java)."""

    def __init__(self, data_schema: TableSchema, params: Optional[Params] = None, **kwargs):
        super().__init__(params, **kwargs)
        self.data_schema = data_schema

    def get_output_schema(self) -> TableSchema:  # pragma: no cover - interface
        raise NotImplementedError

    def map_table(self, data: MTable) -> MTable:  # pragma: no cover - interface
        raise NotImplementedError

    def map_row(self, row: Tuple) -> Tuple:
        """Single-row path for embedded serving; default via 1-row batch."""
        one = MTable([row], self.data_schema)
        return self.map_table(one).row(0)

    def serving_kernel(self):
        """The mapper's compiled-serving contract, or ``None``.

        Mappers whose scoring splits into (host encode -> pure device
        score -> host decode) return a
        :class:`alink_tpu.serving.predictor.ServingKernel`, which the
        serving tier lowers into per-(model signature, shape bucket)
        jitted programs. ``None`` (the default) keeps the mapper on the
        host path — ``CompiledPredictor.for_mapper`` falls back
        gracefully."""
        return None


class ModelMapper(Mapper):
    """Mapper initialized from model rows (reference ModelMapper.loadModel,
    common/mapper/ModelMapperAdapter.java:36-40)."""

    def __init__(self, model_schema: TableSchema, data_schema: TableSchema,
                 params: Optional[Params] = None, **kwargs):
        super().__init__(data_schema, params, **kwargs)
        self.model_schema = model_schema

    def load_model(self, model_table: MTable):  # pragma: no cover - interface
        raise NotImplementedError

    def _pred_output_schema(self, label_type: str,
                            regression: bool) -> TableSchema:
        """The standard prediction-output contract: a prediction column
        (DOUBLE for regression, the model's label type otherwise), an
        optional STRING detail column for classifiers, reserved input
        columns merged by :class:`OutputColsHelper`. One implementation
        so a mapper's declared schema (the stream twins' ``_open``) can
        never drift from what its emit path builds."""
        from ..common.types import AlinkTypes
        pred_col = self.params._m.get("prediction_col", "pred")
        detail_col = self.params._m.get("prediction_detail_col")
        reserved = self.params._m.get("reserved_cols")
        if regression:
            cols, types = [pred_col], [AlinkTypes.DOUBLE]
        else:
            cols, types = [pred_col], [label_type]
            if detail_col:
                cols.append(detail_col)
                types.append(AlinkTypes.STRING)
        return OutputColsHelper(self.data_schema, cols, types,
                                reserved).get_output_schema()
