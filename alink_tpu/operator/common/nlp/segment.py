"""Chinese word segmentation.

Re-design of common/nlp/jiebasegment/ (the reference bundles a jieba port
with a 350k-entry dictionary + HMM Viterbi for OOV). This is an original
implementation of the standard dictionary-DAG + dynamic-programming
algorithm: build the DAG of in-dictionary spans over the sentence, pick the
max-log-frequency path, emit unmatched CJK runs as single characters and
keep latin/digit runs whole. Ships a compact demo dictionary; real use
supplies a user dictionary (``user_defined_dict`` param, same contract as
the reference's userDefinedDict).
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ....common.params import ParamInfo
from .text import TokenizerMapper

# Compact built-in dictionary: (word, frequency). Original list of very
# common Mandarin words — a stand-in for the reference's bundled dict.
_BUILTIN_DICT: Dict[str, int] = {
    "我": 5000, "你": 5000, "他": 5000, "她": 4000, "它": 3000,
    "我们": 3000, "你们": 2000, "他们": 2500, "的": 20000, "了": 9000,
    "是": 9000, "在": 8000, "有": 7000, "和": 6000, "不": 6000,
    "人": 5000, "这": 5000, "那": 4000, "个": 5000, "上": 4000,
    "下": 3500, "来": 4000, "去": 3500, "说": 3500, "要": 3500,
    "就": 3500, "会": 3200, "着": 3000, "没有": 2500, "看": 2800,
    "好": 3000, "自己": 2200, "很": 2600, "到": 3200, "也": 3200,
    "都": 3000, "对": 2600, "能": 2800, "可以": 2400, "中国": 2200,
    "北京": 1500, "上海": 1400, "大学": 1600, "学生": 1500, "老师": 1400,
    "学习": 1500, "机器": 900, "学习机": 200, "机器学习": 1200,
    "深度": 800, "深度学习": 1000, "人工": 700, "智能": 900,
    "人工智能": 1100, "数据": 1300, "大数据": 900, "算法": 1100,
    "模型": 1200, "训练": 1100, "分布式": 800, "计算": 1100, "平台": 900,
    "系统": 1000, "软件": 900, "工程": 900, "科学": 1000, "技术": 1100,
    "开发": 1000, "程序": 900, "程序员": 700, "语言": 900, "中文": 800,
    "分词": 600, "文本": 800, "分析": 900, "处理": 900, "自然": 800,
    "自然语言": 700, "自然语言处理": 650, "今天": 1500, "明天": 1200,
    "昨天": 1100, "天气": 900, "非常": 1300, "喜欢": 1200, "工作": 1400,
    "时间": 1300, "问题": 1300, "因为": 1200, "所以": 1200, "如果": 1100,
    "什么": 1500, "怎么": 1200, "为什么": 900, "知道": 1300, "觉得": 1000,
    "使用": 1000, "服务": 900, "公司": 1200, "世界": 1100, "国家": 1100,
    "朋友": 1100, "孩子": 1000, "东西": 1000, "事情": 1000, "生活": 1100,
}

_CJK = re.compile(r"[一-鿿]+")
_NON_CJK_TOKEN = re.compile(r"[a-zA-Z0-9_]+|[^\s一-鿿]")


class SegmentDict:
    def __init__(self, extra_words: Optional[Sequence[str]] = None):
        self.freq: Dict[str, int] = dict(_BUILTIN_DICT)
        for w in extra_words or []:
            self.freq[str(w)] = max(self.freq.get(str(w), 0), 1000)
        self.total = sum(self.freq.values())
        self.max_len = max((len(w) for w in self.freq), default=1)

    def cut_cjk(self, s: str) -> List[str]:
        """Max-probability path over the in-dictionary DAG."""
        n = len(s)
        logtotal = math.log(self.total)
        # best[i] = (score, j) meaning s[i:j] starts the best path from i
        best: List[Tuple[float, int]] = [(float("-inf"), 0)] * (n + 1)
        best[n] = (0.0, n)
        for i in range(n - 1, -1, -1):
            cands = []
            for j in range(i + 1, min(n, i + self.max_len) + 1):
                w = s[i:j]
                f = self.freq.get(w)
                if f is None and j > i + 1:
                    continue
                logp = (math.log(f) - logtotal) if f else (math.log(1) - logtotal - 10.0)
                cands.append((logp + best[j][0], j))
            best[i] = max(cands) if cands else (best[i + 1][0], i + 1)
        out, i = [], 0
        while i < n:
            j = best[i][1]
            out.append(s[i:j])
            i = j
        return out

    def cut(self, text: str) -> List[str]:
        out: List[str] = []
        pos = 0
        for m in _CJK.finditer(text):
            for tok in _NON_CJK_TOKEN.findall(text[pos:m.start()]):
                out.append(tok)
            out.extend(self.cut_cjk(m.group()))
            pos = m.end()
        for tok in _NON_CJK_TOKEN.findall(text[pos:]):
            out.append(tok)
        return out


class SegmentMapper(TokenizerMapper):
    """reference: nlp/SegmentMapper (jieba port) — space-joined tokens."""

    USER_DEFINED_DICT = ParamInfo("user_defined_dict", list, "extra dictionary words")

    def __init__(self, data_schema, params=None, **kwargs):
        super().__init__(data_schema, params, **kwargs)
        self._dict = SegmentDict(self.params._m.get("user_defined_dict"))

    def _map_text(self, s):
        if s is None:
            return None
        return " ".join(self._dict.cut(str(s)))
