"""CompiledPredictor — per-model jitted serving programs, shape-bucketed.

The reference applies a model per row through ``ModelMapperAdapter.map``
(common/mapper/ModelMapperAdapter.java:42-45); the mappers here are
batched but HOST-side numpy. Serving traffic needs the score kernel on
the device without paying one XLA compile per request size, so:

* a :class:`ServingKernel` (built by the mapper, ``Mapper.
  serving_kernel()``) splits model application into ``encode`` (host:
  rows -> padded arrays), ``device_fn`` (pure jittable scoring) and
  ``decode`` (host: device scores -> output table, the mapper's own
  label/detail logic);
* the predictor compiles ``device_fn`` once per **(model signature,
  encoding kind, shape bucket)** — request batches pad with zero rows to
  the smallest covering bucket from ``ALINK_TPU_SERVE_BUCKETS``, so a
  handful of programs cover arbitrary request sizes and every program
  is reused across requests AND across hot-swapped models of the same
  geometry (weights are *arguments*, never baked into the trace);
* padding rows are numerical no-ops: per-row scoring is row-independent,
  so the real rows of a padded batch are bitwise-identical to the same
  rows served unpadded (tests/test_serving.py pins it).

Hot model swap is double-buffered: :meth:`CompiledPredictor.swap_model`
builds the new model version — mapper load, kernel extraction,
``device_put`` of the weights — entirely in the *standby* slot on the
caller's thread, then flips the active-slot reference atomically.  A
dispatch in flight keeps its own reference to the version it started
with, so no request ever sees a torn model and a swap never blocks the
serving loop.

Cache-key discipline: the predictor resolves ONE :class:`~alink_tpu.
serving.plan.ServingPlan` at construction (kernel signature x bucket
set x sharded mode x mesh fingerprint) and every program-cache key
derives from ``plan.program_key(kind, bucket, shapes)`` — everything
that can change a compiled program is IN the plan or the per-dispatch
dimensions (the mesh fingerprint covers sharded-vs-single-device AND
the device set), so the ``ALINK_TPU_SERVE_*`` flags are declared
key-neutral in ``common/flags.py`` and alink-lint's ENV-KEY-FOLD rule
checks this module as a factory root. The fleet registry
(``serving/fleet.py``) groups same-geometry tenants on the same plan's
``geometry_key()``.

Multi-chip serving (ISSUE 11) lives in :mod:`alink_tpu.serving.sharded`:
``sharded=True`` compiles the bucket programs under the session mesh's
partition rules and places model arrays by their kernel-declared rules;
``ensure_replicas`` pins per-replica single-device placements for the
server's replica fan-out.
"""

from __future__ import annotations

import threading
import time
import warnings
from dataclasses import dataclass, replace
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common import aotcache, compileledger, reqtrace
from ..common.plan import serving_event_plan
from ..common.faults import maybe_crash
from ..common.metrics import get_registry, metrics_enabled
from ..common.mtable import MTable
from ..common.tracing import trace_complete, trace_span
from .plan import ServingPlan
from .sharded import (SERVE_LANES, mesh_fingerprint,
                      serve_sharded_enabled, serving_mesh)

DEFAULT_BUCKETS = (1, 8, 32, 128, 512)

# -- fallback observability (ISSUE 11 satellite) ----------------------------
# The host-mapper fallback used to be SILENT: a mapper without a
# serving kernel (or a predictor that cannot satisfy a sharding
# request) just quietly served off-device and the fleet-scale numbers
# looked mysteriously flat. Every fallback now records a once-per-
# (mapper, reason) RuntimeWarning plus a labelled counter — the shared
# ``common.metrics.record_fallback_once`` machinery (the tuning sweep's
# fallback contract rides the same helper).


def record_serve_fallback(mapper_name: str, reason: str,
                          detail: str = "") -> None:
    """Record one serving-tier fallback: ``alink_serve_fallback_total
    {mapper=, reason=}`` always, plus ONE RuntimeWarning per
    (mapper, reason) pair per process.

    ``reason`` must be a SMALL ENUM of stable strings — it is a metric
    label, and data-dependent text (exception messages carry request
    widths etc.) would mint a new time series per distinct value.
    Request-specific context goes in ``detail``, which reaches only the
    warning text."""
    from ..common.metrics import record_fallback_once
    record_fallback_once(
        "serve", "alink_serve_fallback_total",
        {"mapper": mapper_name, "reason": reason},
        f"serving falls back to the host mapper path for {mapper_name}: "
        f"{reason}{' (' + detail + ')' if detail else ''} (recorded as "
        f"alink_serve_fallback_total{{mapper={mapper_name!r},"
        f"reason={reason!r}}}; this warning fires once per "
        f"mapper+reason)")


def _reset_fallback_warnings() -> None:
    """Test hook: re-arm the once-per-(mapper, reason) warnings."""
    from ..common.metrics import reset_fallback_warnings
    reset_fallback_warnings("serve")


def serve_compiled_enabled() -> bool:
    """``ALINK_TPU_SERVE_COMPILED``: route the stream predict twins
    (ModelMapStreamOp) through the compiled serving path. Default off —
    the flag-off path runs the exact pre-serving host mapper code."""
    from ..common.flags import flag_value
    return flag_value("ALINK_TPU_SERVE_COMPILED", False)


def serve_buckets(default: Sequence[int] = DEFAULT_BUCKETS) -> Tuple[int, ...]:
    """``ALINK_TPU_SERVE_BUCKETS``: the shape-bucket set, sorted unique
    positive ints (comma-separated). The registry parser normalizes;
    this accessor returns the tuple call sites key programs on."""
    from ..common.flags import flag_value
    raw = flag_value("ALINK_TPU_SERVE_BUCKETS", "")
    if not raw:
        return tuple(default)
    return _parse_buckets(raw) or tuple(default)


def serve_window_s() -> float:
    """``ALINK_TPU_SERVE_WINDOW_MS`` (batching latency budget) in
    seconds."""
    from ..common.flags import flag_value
    return float(flag_value("ALINK_TPU_SERVE_WINDOW_MS", 2.0)) / 1e3


def serve_min_fill() -> int:
    """``ALINK_TPU_SERVE_MIN_FILL``: the micro-batcher's fill target —
    batches below it are held up to the window for stragglers. The
    default of 1 keeps pure adaptive dispatch."""
    from ..common.flags import flag_value
    return int(flag_value("ALINK_TPU_SERVE_MIN_FILL", 1))


def serve_queue_depth() -> int:
    """``ALINK_TPU_SERVE_QUEUE``: admission-control bound of the request
    channel (requests beyond it block the submitter — backpressure)."""
    from ..common.flags import flag_value
    return int(flag_value("ALINK_TPU_SERVE_QUEUE", 1024))


def serve_swap_mode() -> str:
    """``ALINK_TPU_SERVE_SWAP``: ``double`` (default — standby slot
    prepared off the serving loop, atomic flip) or ``sync`` (the flip
    additionally blocks until the standby weights are device-resident;
    debugging aid, serving loop still never blocks)."""
    from ..common.flags import flag_value
    return str(flag_value("ALINK_TPU_SERVE_SWAP", "double"))


def _parse_buckets(raw: str) -> Tuple[int, ...]:
    out = []
    for part in str(raw).split(","):
        part = part.strip()
        if not part:
            continue
        out.append(int(part))
    return tuple(sorted({b for b in out if b > 0}))


@dataclass
class ServingKernel:
    """One model's compiled-serving contract (built by the mapper).

    ``signature``     — hashable PROGRAM identity: geometry/dtype/kind of
                        the model, everything that shapes the traced
                        computation EXCEPT the weight values. Two model
                        versions with equal signatures share compiled
                        programs (the hot-swap fast path).
    ``model_arrays``  — the weights, a tuple of host arrays; the
                        predictor ``device_put``s them once per model
                        version and passes them as program arguments.
    ``encode(mt, bucket)`` -> ``(kind, arrays)`` — host encode of a
                        request table, padded with zero rows to
                        ``bucket``; ``kind`` discriminates encodings
                        (dense vs sparse) of the same model.
    ``device_fns[kind](model_arrays, *arrays)`` — pure jittable scoring;
                        outputs are arrays whose leading axis is rows.
    ``decode(outputs, mt)`` — host decode of the REAL-row slice of the
                        program outputs into the mapper's output table
                        (the mapper's own label/detail logic).
    """
    signature: Tuple
    model_arrays: Tuple[np.ndarray, ...]
    encode: Callable[[MTable, int], Tuple[str, Tuple[np.ndarray, ...]]]
    device_fns: Dict[str, Callable]
    decode: Callable[[Tuple[np.ndarray, ...], MTable], MTable]
    # -- multi-chip serving (optional; ISSUE 11) ------------------------
    # ``model_names``       — one name per model array, matched against
    #                         ``partition_rules`` (the io/sharding.py
    #                         match_partition_rules idiom) to place the
    #                         model on the serving mesh;
    # ``partition_rules``   — ((regex, PartitionSpec), ...); unmatched
    #                         names replicate (default P());
    # ``input_specs(kind)`` — PartitionSpecs of the ENCODED request
    #                         arrays under the mesh;
    # ``make_sharded_fns(mesh)`` -> {kind: fn} — mesh-sharded twins of
    #                         ``device_fns`` (shard_map + manifest
    #                         collectives). ``None`` = the kernel cannot
    #                         shard; a sharding request falls back
    #                         (recorded) to single-device programs.
    model_names: Tuple[str, ...] = ()
    partition_rules: Tuple = ()
    input_specs: Optional[Callable[[str], Tuple]] = None
    make_sharded_fns: Optional[Callable] = None
    # -- multi-tenant fleet coalescing (optional; ISSUE 17) -------------
    # ``make_fleet_fns()`` -> {kind: fn(stacked_model_arrays, lane,
    #                          *arrays)} — lane-stacked twins of
    #                         ``device_fns``: each model array gains a
    #                         leading tenant-lane axis and every request
    #                         row gathers its own tenant's weights via
    #                         the int32 ``lane`` vector (the tuning
    #                         ``(points,)`` carry-lane idiom). Per-row
    #                         arithmetic and reduction order must be
    #                         IDENTICAL to ``device_fns`` so cross-
    #                         tenant coalescing is a bitwise no-op.
    #                         ``None`` = the kernel cannot coalesce; the
    #                         fleet serves its tenants through per-
    #                         tenant dispatch (fallback recorded).
    make_fleet_fns: Optional[Callable] = None


def _merge_parts(parts):
    """Concatenate chunk outputs column-wise in ONE pass — a pairwise
    ``concat_rows`` fold re-copies the growing table per part, O(p^2)
    data movement on the routed-stream hot path."""
    first = parts[0]
    cols = {}
    for nm in first.col_names:
        arrs = []
        for p in parts:
            c = p.col(nm)
            if getattr(c, "__mtable_column__", False):
                c = c.materialize()
            arrs.append(c)
        if any(a.dtype == object for a in arrs):
            out = np.empty(sum(a.shape[0] for a in arrs), object)
            off = 0
            for a in arrs:
                out[off:off + a.shape[0]] = a
                off += a.shape[0]
        else:
            out = np.concatenate(arrs)
        cols[nm] = out
    return MTable(cols, first.schema)


class _ModelVersion:
    """One immutable model slot: kernel + device-resident weights.

    ``shardings`` (multi-chip serving) places each model array with its
    matched ``NamedSharding`` — host arrays ``device_put`` STRAIGHT into
    their mesh placement (no replicated staging copy), and arrays that
    are already device-resident with the right sharding pass through
    without a host round trip (the FTRL in-place swap path).
    ``devices`` (replica dispatch) materializes one placement per
    replica device instead."""

    __slots__ = ("version", "kernel", "mapper", "_placements")

    def __init__(self, version: int, kernel: ServingKernel, mapper=None,
                 shardings: Optional[Tuple] = None,
                 devices: Tuple = (None,)):
        import jax
        self.version = version
        self.kernel = kernel
        self.mapper = mapper
        # the weights land on device HERE — on the swapping thread, not
        # the serving loop (the double-buffer contract)
        if shardings is not None:
            self._placements = (tuple(
                jax.device_put(a, s)
                for a, s in zip(kernel.model_arrays, shardings)),)
        else:
            self._placements = tuple(
                tuple(jax.device_put(a) if d is None
                      else jax.device_put(a, d)
                      for a in kernel.model_arrays)
                for d in devices)

    def arrays_for(self, replica: int = 0) -> Tuple:
        return self._placements[replica % len(self._placements)]

    def block_until_ready(self) -> None:
        """Wait for EVERY placement (all replicas / all shards) — the
        sync-swap contract covers each replica's device copy, not just
        slot 0's."""
        import jax
        jax.block_until_ready([a for p in self._placements for a in p])

    @property
    def device_arrays(self) -> Tuple:
        return self._placements[0]


class CompiledPredictor:
    """Shape-bucketed compiled model application with hot swap.

    ``CompiledPredictor(mapper)`` takes a LOADED ModelMapper that
    implements ``serving_kernel()``; :meth:`for_mapper` returns ``None``
    instead of raising for mappers without a kernel (the stream-twin
    routing falls back to the host path).
    """

    def __init__(self, mapper, buckets: Optional[Sequence[int]] = None,
                 name: str = "serve", sharded: Optional[bool] = None,
                 mesh=None, replica_devices: Optional[Sequence] = None):
        kernel = mapper.serving_kernel()
        if kernel is None:
            raise TypeError(
                f"{type(mapper).__name__} does not provide a serving "
                f"kernel; use CompiledPredictor.for_mapper() to fall "
                f"back to the host mapper path")
        self.name = name
        self._buckets = tuple(sorted({int(b) for b in buckets if int(b) > 0})) \
            if buckets else serve_buckets()
        if not self._buckets:
            raise ValueError("empty bucket set")
        # -- multi-chip resolution (ISSUE 11): sharded bucket programs
        # span the serving mesh; replica dispatch pins per-replica
        # single-device placements. Mutually exclusive by construction
        # (a sharded program already uses every chip).
        self._sharded = serve_sharded_enabled() if sharded is None \
            else bool(sharded)
        self._mesh = None
        if self._sharded:
            if kernel.make_sharded_fns is None:
                record_serve_fallback(type(mapper).__name__,
                                      "no-sharded-kernel")
                self._sharded = False
            else:
                m = mesh if mesh is not None else serving_mesh()
                n = int(m.devices.size)
                if SERVE_LANES % n:
                    record_serve_fallback(
                        type(mapper).__name__, "mesh-indivisible",
                        f"{n} devices vs {SERVE_LANES} lanes")
                    self._sharded = False
                else:
                    self._mesh = m
        self._mesh_fp = mesh_fingerprint(self._mesh)
        if self._sharded and replica_devices:
            raise ValueError("sharded serving programs span the mesh; "
                             "replica_devices does not compose with "
                             "sharded=True")
        self._replica_devices: Tuple = tuple(replica_devices) \
            if replica_devices else (None,)
        # ONE resolved plan (ISSUE 17 / ROADMAP item 1): every program
        # key, the fleet's geometry grouping and the swap signature
        # derive from it instead of re-threading buckets/dtype/fused/
        # sharded/mesh by hand at each site
        self.plan = ServingPlan(signature=kernel.signature,
                                buckets=self._buckets,
                                sharded=self._sharded,
                                mesh_fp=self._mesh_fp)
        # compile-ledger identity (ISSUE 19): one ledger cache per
        # predictor; every miss in _program records an event whose diff
        # names the changed dimension (dtype flip, new bucket, swapped
        # geometry)
        self._ledger_cache = f"serve.{self.name}"
        compileledger.register_cache(self._ledger_cache, "serving")
        compileledger.subsystem_start("serving")
        self._sharded_fns: Dict[Tuple, Dict[str, Callable]] = {}
        self._swap_lock = threading.Lock()
        self._cache_lock = threading.Lock()
        self._programs: Dict[Tuple, Tuple[Callable, Tuple]] = {}
        self._hits = 0
        self._hits_reported = 0
        self._misses = 0
        self._versions = 0
        # slot 0 = active. The standby slot is materialized per swap
        # (a fresh _ModelVersion) and flipped in by ONE reference store,
        # so readers racing a swap see either the old or the new version
        # whole — never a mix.
        self._active = self._make_version(kernel, mapper)

    # ------------------------------------------------------------------
    @classmethod
    def for_mapper(cls, mapper, buckets: Optional[Sequence[int]] = None,
                   name: str = "serve", **kw) -> Optional["CompiledPredictor"]:
        """A predictor, or ``None`` when the mapper has no kernel — and
        the fallback is RECORDED (``alink_serve_fallback_total`` + one
        RuntimeWarning per mapper+reason), never silent."""
        try:
            kernel = mapper.serving_kernel()
            reason, detail = "no-serving-kernel", ""
        except RuntimeError as e:
            kernel = None
            reason, detail = "kernel-error", str(e)
        if kernel is None:
            record_serve_fallback(type(mapper).__name__, reason, detail)
            return None
        return cls(mapper, buckets=buckets, name=name, **kw)

    def _ver_sharded(self, kernel: ServingKernel) -> bool:
        """Does THIS kernel run sharded on this predictor? A hot swap
        can hand a sharded predictor a kernel that cannot shard (e.g. a
        softmax model swapped into a binary slot) — that version serves
        through single-device programs (fallback recorded in
        :meth:`_make_version`) instead of crashing every dispatch."""
        return self._sharded and kernel.make_sharded_fns is not None

    def _model_shardings(self, kernel: ServingKernel) -> Optional[Tuple]:
        """NamedShardings of the model arrays under the partition rules
        (None when unsharded): the ``io/sharding.py`` placement path —
        ``match_partition_rules`` over the kernel's named arrays, every
        unmatched name replicated."""
        if not self._ver_sharded(kernel):
            return None
        from jax.sharding import PartitionSpec as P

        from ..io.sharding import state_sharding
        names = kernel.model_names or tuple(
            f"a{i}" for i in range(len(kernel.model_arrays)))
        named = dict(zip(names, kernel.model_arrays))
        sh = state_sharding(self._mesh, kernel.partition_rules, named,
                            default=P())
        return tuple(sh[n] for n in names)

    def _make_version(self, kernel: ServingKernel, mapper) -> _ModelVersion:
        self._versions += 1
        if self._sharded and kernel.make_sharded_fns is None:
            record_serve_fallback(type(mapper).__name__,
                                  "no-sharded-kernel (swapped model "
                                  "serves single-device)")
        return _ModelVersion(self._versions, kernel, mapper,
                             shardings=self._model_shardings(kernel),
                             devices=self._replica_devices)

    # -- replica dispatch (ISSUE 11) ------------------------------------
    def ensure_replicas(self, devices: Sequence) -> None:
        """Materialize per-replica model placements (one device per
        replica) — called by :class:`~alink_tpu.serving.server.
        PredictServer` before it spawns replica loops. Re-places the
        ACTIVE version; later swaps inherit the device list."""
        devices = tuple(devices)
        if not devices or self._sharded:
            return
        with self._swap_lock:
            if devices == self._replica_devices:
                return
            self._replica_devices = devices
            cur = self._active
            self._active = _ModelVersion(cur.version, cur.kernel,
                                         cur.mapper, devices=devices)

    @property
    def replica_devices(self) -> Tuple:
        return self._replica_devices

    # -- model hot swap -------------------------------------------------
    def swap_model(self, model_table: MTable) -> int:
        """Load ``model_table`` into the standby slot and flip it active.

        Runs entirely on the caller's thread (the model-stream tap):
        mapper construction, ``load_model``, kernel extraction and the
        weight ``device_put`` all happen BEFORE the flip, which is one
        atomic reference store. Returns the new version number.
        Serialized across swappers; never blocks the serving loop."""
        with self._swap_lock:
            t0 = time.perf_counter()
            # fault site: an error-mode fault fails the swap BEFORE the
            # standby build — the active version never flips, so the
            # last good model keeps serving (the feeder-supervision
            # contract this site exists to test)
            maybe_crash("serve.swap")
            with trace_span("serve.swap", cat="serve"):
                base = self._active.mapper
                mapper = type(base)(model_table.schema, base.data_schema,
                                    base.params)
                mapper.load_model(model_table)
                standby = self._make_version(mapper.serving_kernel(), mapper)
                if serve_swap_mode() == "sync":
                    standby.block_until_ready()
                self._active = standby     # the atomic flip
            dt = time.perf_counter() - t0
        # stamp the flip onto every request in flight: a tail exemplar
        # overlapping this swap names it (ISSUE 18)
        reqtrace.annotate_inflight("swap", {"predictor": self.name,
                                            "version": standby.version})
        if metrics_enabled():
            reg = get_registry()
            reg.inc("alink_serve_model_swaps_total", 1,
                    {"predictor": self.name})
            reg.observe("alink_serve_swap_seconds", dt,
                        {"predictor": self.name})
        return standby.version

    def swap_weights(self, model_arrays: Sequence) -> int:
        """Same-geometry in-place weight swap: install ``model_arrays``
        (host or device arrays, matching the ACTIVE kernel's shapes)
        as a new model version WITHOUT reloading a model table.

        This is the no-gather-to-host leg of multi-chip serving: a
        feature-sharded producer (the FTRL trainer's (z, n)-derived
        weights) hands arrays that are already in — or go straight
        into — their mesh placement; ``jax.device_put`` with the
        matched ``NamedSharding`` is a no-op for correctly-placed
        device arrays. The mapper's host-side decode state (labels,
        detail schema) is geometry, not weights, so it carries over.
        The flip is the same atomic reference store as
        :meth:`swap_model`."""
        with self._swap_lock:
            t0 = time.perf_counter()
            maybe_crash("serve.swap")   # same site as swap_model: both
                                        # are the feeder's swap boundary
            with trace_span("serve.swap", cat="serve",
                            args={"mode": "weights"}):
                base = self._active
                arrays = tuple(model_arrays)
                if len(arrays) != len(base.kernel.model_arrays):
                    raise ValueError(
                        f"swap_weights got {len(arrays)} arrays; the "
                        f"active kernel has "
                        f"{len(base.kernel.model_arrays)}")
                for a, old in zip(arrays, base.kernel.model_arrays):
                    if tuple(a.shape) != tuple(old.shape) \
                            or np.dtype(a.dtype) != np.dtype(old.dtype):
                        raise ValueError(
                            f"swap_weights geometry mismatch: "
                            f"{tuple(a.shape)}/{np.dtype(a.dtype)} vs "
                            f"{tuple(old.shape)}/{np.dtype(old.dtype)} — "
                            f"a different geometry must go through "
                            f"swap_model (new signature, new programs)")
                kernel = replace(base.kernel, model_arrays=arrays)
                standby = self._make_version(kernel, base.mapper)
                if serve_swap_mode() == "sync":
                    standby.block_until_ready()
                self._active = standby     # the atomic flip
            dt = time.perf_counter() - t0
        reqtrace.annotate_inflight("swap", {"predictor": self.name,
                                            "version": standby.version,
                                            "mode": "weights"})
        if metrics_enabled():
            reg = get_registry()
            reg.inc("alink_serve_model_swaps_total", 1,
                    {"predictor": self.name})
            reg.observe("alink_serve_swap_seconds", dt,
                        {"predictor": self.name})
        return standby.version

    @property
    def model_version(self) -> int:
        return self._active.version

    @property
    def sharded(self) -> bool:
        return self._sharded

    @property
    def mesh(self):
        return self._mesh

    @property
    def buckets(self) -> Tuple[int, ...]:
        return self._buckets

    # -- program cache --------------------------------------------------
    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (requests larger than the top bucket are
        served in top-bucket chunks)."""
        for b in self._buckets:
            if n <= b:
                return b
        return self._buckets[-1]

    def _sharded_fn(self, kernel: ServingKernel, kind: str) -> Callable:
        """The mesh-sharded device fn for ``kind`` — built once per
        (kernel signature, mesh) via the kernel's ``make_sharded_fns``
        factory and shared by every bucket program and model version of
        that geometry. Callers hold ``_cache_lock``."""
        fkey = (kernel.signature, self._mesh_fp)
        fns = self._sharded_fns.get(fkey)
        if fns is None:
            fns = self._sharded_fns[fkey] = kernel.make_sharded_fns(
                self._mesh)
        return fns[kind]

    def _place_inputs(self, ver: _ModelVersion, kind: str,
                      arrays: Tuple[np.ndarray, ...], replica: int
                      ) -> Tuple:
        """Encoded request arrays -> device: under sharding each input
        lands with its kernel-declared PartitionSpec (the feature axis
        of the dense design matrix shards alongside the weights); under
        replica dispatch each lands on the replica's device; otherwise
        the arrays pass through and jit commits them (the historical
        single-device path)."""
        if self._ver_sharded(ver.kernel) \
                and ver.kernel.input_specs is not None:
            import jax
            from jax.sharding import NamedSharding
            specs = ver.kernel.input_specs(kind)
            return tuple(jax.device_put(a, NamedSharding(self._mesh, s))
                         for a, s in zip(arrays, specs))
        dev = self._replica_devices[replica % len(self._replica_devices)]
        if dev is not None:
            import jax
            return tuple(jax.device_put(a, dev) for a in arrays)
        return arrays

    def _program(self, ver: _ModelVersion, kind: str, bucket: int,
                 arrays: Tuple, call_args: Tuple
                 ) -> Tuple[Callable, Tuple]:
        """The compiled program for (model signature, kind, bucket,
        mesh) — every dimension that shapes the trace is part of the key
        (leading axes are the bucket itself; dtypes are fixed by the
        kernel signature; the mesh fingerprint covers sharded-vs-single-
        device and the device set), so a cache hit can never serve a
        stale program. The hit path is lock-free (GIL-atomic dict read +
        int bump) — it runs per dispatched batch on the serving loop.

        Returns ``(program, manifest)``: sharded programs additionally
        carry their trace-time collective manifest, captured ONCE via an
        AOT ``lower`` inside :func:`~alink_tpu.engine.communication.
        collecting` and replayed per dispatch by the caller — serving
        traffic shows up in the collective manifest/metrics exactly like
        training traffic."""
        sharded = self._ver_sharded(ver.kernel)
        key = self.plan.program_key(
            kind, bucket, tuple(a.shape[1:] for a in arrays),
            signature=ver.kernel.signature, sharded=sharded)
        entry = self._programs.get(key)
        if entry is not None:
            self._hits += 1
            compileledger.record_hit(self._ledger_cache)
            return entry
        import jax
        _led_t0 = time.perf_counter()
        with self._cache_lock:
            entry = self._programs.get(key)
            if entry is None:
                self._misses += 1
                evplan = serving_event_plan(
                    self.plan, signature=ver.kernel.signature,
                    sharded=sharded, kind=kind, bucket=bucket,
                    trailing=tuple(a.shape[1:] for a in arrays))
                # load-before-compile (ISSUE 20): an exported executable
                # for this exact plan digest installs instead of a fresh
                # trace+compile. Sharded programs stay on the compile
                # path — their trace captures the collective manifest.
                if not sharded and aotcache.active():
                    loaded = aotcache.load(
                        evplan, cache=self._ledger_cache,
                        site="CompiledPredictor._program",
                        subsystem="serving")
                    if loaded is not None:
                        entry = (loaded.fn, ())
                        self._programs[key] = entry
                        if metrics_enabled():
                            get_registry().inc(
                                "alink_serve_program_cache_total", 1,
                                {"result": "disk-hit",
                                 "predictor": self.name})
                        return entry
                if sharded:
                    fn = self._sharded_fn(ver.kernel, kind)
                else:
                    fn = ver.kernel.device_fns[kind]
                prog = jax.jit(fn)
                manifest: Tuple = ()
                if sharded:
                    from ..engine.communication import collecting
                    cap: List = []
                    try:
                        with collecting(cap):
                            prog.lower(ver.arrays_for(0), *call_args)
                    except Exception as e:  # accounting must never
                        cap = []            # break serving — but say so
                        warnings.warn(
                            f"serving collective accounting disabled "
                            f"for program {key[:3]} (AOT lower failed: "
                            f"{e!r})", RuntimeWarning)
                    manifest = tuple(cap)
                entry = (prog, manifest)
                self._programs[key] = entry
                compileledger.record_event(
                    self._ledger_cache, evplan,
                    wall_s=time.perf_counter() - _led_t0,
                    site="CompiledPredictor._program",
                    subsystem="serving")
                if not sharded and aotcache.active():
                    aotcache.store(
                        evplan, prog,
                        (ver.arrays_for(0),) + tuple(call_args),
                        cache=self._ledger_cache,
                        site="CompiledPredictor._program", key=key)
                if metrics_enabled():
                    get_registry().inc("alink_serve_program_cache_total",
                                       1, {"result": "miss",
                                           "predictor": self.name})
            else:
                self._hits += 1
                compileledger.record_hit(self._ledger_cache)
        return entry

    def warm_from_disk(self) -> int:
        """Admission warming (ISSUE 20): install every AOT artifact in
        this predictor's cache directory whose program-cache key, when
        re-derived against THIS predictor's plan, still digests to the
        artifact's plan digest — the bucket x dtype grid of a previous
        process loads before the first request instead of compiling on
        it.  Foreign or drifted artifacts are skipped (a fingerprint
        mismatch refuses loudly inside :func:`aotcache.load`); returns
        how many programs were installed."""
        if not aotcache.active():
            return 0
        import ast
        n = 0
        for _path, header in aotcache.scan(self._ledger_cache):
            try:
                key = ast.literal_eval(header.get("key_repr") or "")
            except Exception:
                continue
            if not isinstance(key, tuple) or len(key) != 7:
                continue
            sig, kind, bucket, trailing, buckets, lanes, mesh_fp = key
            if lanes is not None or mesh_fp is not None:
                continue          # fleet-lane / sharded: not this cache
            if tuple(buckets) != self._buckets:
                continue
            evplan = serving_event_plan(
                self.plan, signature=sig, sharded=False, kind=kind,
                bucket=bucket, trailing=tuple(trailing))
            if evplan.digest() != header.get("plan_digest"):
                continue          # geometry drifted: a plain miss
            # install under the key _program would derive TODAY (the
            # artifact's stored repr is advisory, the derivation is
            # authoritative)
            key = self.plan.program_key(kind, bucket, tuple(trailing),
                                        signature=sig, sharded=False)
            with self._cache_lock:
                if key in self._programs:
                    continue
            loaded = aotcache.load(
                evplan, cache=self._ledger_cache,
                site="CompiledPredictor.warm_from_disk",
                subsystem="serving")
            if loaded is None:
                continue
            with self._cache_lock:
                if key not in self._programs:
                    self._programs[key] = (loaded.fn, ())
                    n += 1
        return n

    def cache_stats(self) -> Dict[str, int]:
        self.flush_metrics()
        with self._cache_lock:
            return {"hits": self._hits, "misses": self._misses,
                    "programs": len(self._programs)}

    def flush_metrics(self) -> None:
        """Push the (lock-free) hit counter delta into the registry —
        per-hit registry updates would tax every dispatched batch, so
        hits batch up and flush at stats/accounting boundaries."""
        if not metrics_enabled():
            return
        with self._cache_lock:
            delta = self._hits - self._hits_reported
            self._hits_reported = self._hits
        if delta > 0:
            get_registry().inc("alink_serve_program_cache_total", delta,
                               {"result": "hit", "predictor": self.name})

    # -- prediction -----------------------------------------------------
    def predict_table(self, data: MTable, replica: int = 0) -> MTable:
        """Serve a whole request table through the bucketed programs.

        Output is bitwise-identical for the real rows no matter which
        bucket (or chunk split) served them — padding rows are zero and
        per-row scoring is row-independent. ``replica`` selects the
        replica-dispatch device placement (0 = default)."""
        n = data.num_rows
        if n == 0:
            return self._active.mapper.map_table(data)
        top = self._buckets[-1]
        if n <= top:
            return self._predict_chunk(data, replica)
        parts = [self._predict_chunk(
                     data.take_rows(np.arange(s, min(s + top, n))), replica)
                 for s in range(0, n, top)]
        return _merge_parts(parts)

    def _predict_chunk(self, data: MTable, replica: int = 0) -> MTable:
        import jax
        t0 = time.perf_counter()
        # deterministic fault site (common/faults.py): error = a
        # catchable transient dispatch failure (what trips the serving
        # circuit breaker), delay:MS = latency injection, kill = the
        # loop-supervisor/respawn path. BEFORE encode: a shed/failed
        # dispatch must not have paid any device work
        maybe_crash("serve.dispatch")
        ver = self._active           # one consistent model per dispatch
        n = data.num_rows
        bucket = self.bucket_for(n)
        kind, arrays = ver.kernel.encode(data, bucket)
        placed = self._place_inputs(ver, kind, arrays, replica)
        prog, manifest = self._program(ver, kind, bucket, arrays, placed)
        if manifest:
            from ..engine.communication import collecting, record_manifest
            record_manifest(manifest)
            # the replayed manifest is the ONLY accounting: should the
            # call retrace (jax version didn't warm the call cache from
            # the AOT lower), its trace-time records land in a discarded
            # sink instead of double-charging the registry — the FTRL
            # drain's collecting([]) idiom
            with collecting([]):
                out = prog(ver.arrays_for(replica), *placed)
        else:
            out = prog(ver.arrays_for(replica), *placed)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        # request-timeline phase boundaries (ISSUE 18): dispatch work
        # (encode + placement + program launch) ends here; the device
        # wait is the host fetch; decode is the tail. No-ops outside a
        # server batch scope — pure host bookkeeping either way.
        reqtrace.batch_mark("dispatch")
        # ONE batched host fetch, then slice the padding rows off
        host = jax.device_get(list(out))
        reqtrace.batch_mark("device")
        sliced = tuple(np.asarray(a)[:n] for a in host)
        result = ver.kernel.decode(sliced, data)
        reqtrace.batch_mark("decode")
        trace_complete("serve.batch", time.perf_counter() - t0, cat="serve",
                       args={"rows": n, "bucket": bucket,
                             "model_version": ver.version})
        if metrics_enabled():
            reg = get_registry()
            lbl = {"predictor": self.name}
            reg.inc("alink_serve_batches_total", 1, lbl)
            reg.observe("alink_serve_batch_occupancy", n / bucket, lbl)
        return result

    def predict_row(self, row: Tuple) -> Tuple:
        """LocalPredictor-style single-row serving: the 1-row table trip
        through the bucket-1 program (this is the serial-dispatch
        baseline the micro-batcher is measured against)."""
        one = MTable([row], self._active.mapper.data_schema)
        return self.predict_table(one).row(0)

    # -- parity helpers -------------------------------------------------
    def host_reference(self, data: MTable) -> MTable:
        """The active model applied through the HOST mapper path
        (``map_table``) — the parity baseline of the compiled tier."""
        return self._active.mapper.map_table(data)

    @property
    def output_schema(self):
        return self._active.mapper.get_output_schema()

    @property
    def data_schema(self):
        return self._active.mapper.data_schema
