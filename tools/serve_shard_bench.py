"""Multi-chip serving bench + gate smoke (ISSUE 11).

Measures the sharded serving tier at REAL 1/4/8-device host-platform
meshes. XLA device counts latch at backend init, so each mesh size runs
in a FRESH interpreter (``bootenv.cpu_mesh_env`` — the
``tools/scaling_evidence.py`` mechanism). Every child builds the SAME
deterministic feature-sharded linear model (synthetic weights, no
training — trainers would converge differently per mesh), serves a
closed-loop load through ``PredictServer`` over sharded bucket
programs, hot-swaps a deterministic model sequence under load, and
reports:

* ``qps`` / ``qps_per_chip`` — closed-loop load-generator throughput;
* ``digest`` — sha256 over the rendered predictions of a fixed probe
  table: equal digests across children == measured BITWISE parity of
  the sharded bucket programs at mesh 1 vs 4 vs 8;
* ``torn`` / ``failed`` — swap-storm integrity (every response must
  match one model version that was ever active).

Modes:
  ``--child``     (internal) one mesh size, prints one JSON line;
  ``--json``      parent: spawn children for ``--devices`` (default
                  1,4,8), print the combined serve_logreg_sharded row;
  ``--smoke``     the perf_gate leg: mesh 1 vs 4, parity + zero torn
                  swaps; exits 5 (a DISTINCT gate code) on failure.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)   # children run as a script from tools/
DIM = 96
SEED = 2026


def _build_model_table(seed: int, dim: int = DIM):
    """A deterministic binary LR model table (intercept + dim weights):
    the serving fixture must be IDENTICAL across mesh sizes, so it is
    synthesized, never trained."""
    import numpy as np

    from alink_tpu.common.types import AlinkTypes
    from alink_tpu.operator.common.linear.base import (
        LinearModelData, LinearModelDataConverter, LinearModelType)
    rng = np.random.RandomState(seed)
    coef = rng.randn(dim + 1)
    m = LinearModelData("serve_sharded", LinearModelType.LR, True, "vec",
                        None, dim, coef, [1, 0], AlinkTypes.LONG)
    return LinearModelDataConverter(AlinkTypes.LONG).save_model(m)


def _fixture(dim: int = DIM, n_rows: int = 256):
    import numpy as np

    from alink_tpu.common.mtable import MTable
    from alink_tpu.common.params import Params
    from alink_tpu.common.vector import DenseVector
    from alink_tpu.operator.common.linear.mapper import LinearModelMapper
    rng = np.random.RandomState(SEED + 1)
    X = rng.randn(n_rows, dim)
    vecs = np.empty(n_rows, object)
    vecs[:] = [DenseVector(X[i]) for i in range(n_rows)]
    tbl = MTable({"vec": vecs}, "vec VECTOR")
    model = _build_model_table(SEED)
    mapper = LinearModelMapper(
        model.schema, tbl.schema,
        Params({"prediction_col": "pred", "prediction_detail_col": "det",
                "vector_col": "vec"}))
    mapper.load_model(model)
    return tbl, mapper


def _digest(table) -> str:
    h = hashlib.sha256()
    for i in range(table.num_rows):
        h.update(repr(tuple(map(str, table.row(i)))).encode())
    return h.hexdigest()[:16]


def run_child(n_devices: int, requests: int, swaps: int) -> dict:
    """One mesh size, inside an interpreter whose XLA host platform was
    widened to ``n_devices`` BEFORE jax loaded."""
    import jax

    from alink_tpu.common.mlenv import use_local_env
    from alink_tpu.serving import (CompiledPredictor, LoadGenerator,
                                   PredictServer)
    assert len(jax.devices()) >= n_devices, (
        f"child expected {n_devices} devices, got {jax.devices()}")
    use_local_env(parallelism=n_devices)
    tbl, mapper = _fixture()
    pred = CompiledPredictor(mapper, sharded=True, name="serve_sharded")
    assert pred.sharded and int(pred.mesh.devices.size) == n_devices
    for b in pred.buckets:                    # compile outside the timing
        pred.predict_table(tbl.first_n(min(b, tbl.num_rows)))
    probe_out = pred.predict_table(tbl)       # the cross-mesh parity probe
    digest = _digest(probe_out)

    rows = [tbl.row(i) for i in range(64)]
    srv = PredictServer(pred, name="serve_sharded")
    lg = LoadGenerator(srv.submit, rows, clients=4, pipeline=16)
    lg.run(max(100, requests // 8))           # warm the loop
    rep = lg.run(requests)

    # deterministic swap storm: every version's probe response is known
    # up front (same program, same mesh -> same bits), so any response
    # outside the set is a torn model
    probe = tbl.row(0)
    tables = [_build_model_table(SEED + 10 + i) for i in range(swaps)]
    expected = {str(pred.predict_row(probe))}
    for t in tables:
        m2 = type(mapper)(t.schema, tbl.schema, mapper.params)
        m2.load_model(t)
        expected.add(str(CompiledPredictor(
            m2, sharded=True, name="ref").predict_row(probe)))
    plg = LoadGenerator(srv.submit, [probe], clients=2, pipeline=8,
                        collect_responses=True)
    results = {"swapped": 0}

    import threading

    def storm():
        for t in tables:
            srv.swap_model(t)
            results["swapped"] += 1
    th = threading.Thread(target=storm)
    th.start()
    srep = plg.run(max(400, requests // 4))
    th.join(60)
    stats = srv.stats()
    srv.close()
    observed = {str(r) for r in srep.responses}
    torn = len(observed - expected)
    return {
        "devices": n_devices,
        "qps": round(rep.qps, 1),
        "qps_per_chip": round(rep.qps / n_devices, 1),
        "p50_ms": round(rep.p50_s * 1e3, 3),
        "p99_ms": round(rep.p99_s * 1e3, 3),
        "digest": digest,
        "model_swaps": results["swapped"],
        "torn_responses": torn,
        "failed_requests": rep.failures + srep.failures + stats["failed"],
        "requests": rep.requests + srep.requests,
        "bucket_hit_rate": round(stats["bucket_hit_rate"], 4),
    }


def _spawn_child(n_devices: int, requests: int, swaps: int,
                 timeout: int = 420) -> dict:
    sys.path.insert(0, ROOT)
    import bootenv
    env = bootenv.cpu_mesh_env(n_devices)
    env.pop("ALINK_TPU_MESH_DEVICES", None)   # the child mesh IS the rig
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--devices", str(n_devices), "--requests", str(requests),
           "--swaps", str(swaps)]
    out = subprocess.run(cmd, cwd=ROOT, env=env, capture_output=True,
                         text=True, timeout=timeout)
    if out.returncode != 0:
        raise RuntimeError(
            f"serve_shard_bench child ({n_devices} devices) failed "
            f"rc={out.returncode}:\n{out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def measure(devices=(1, 4, 8), requests: int = 4000,
            swaps: int = 12) -> dict:
    """The ``serve_logreg_sharded`` bench row: per-mesh-size children,
    cross-mesh bitwise parity via probe digests, QPS/chip trajectory."""
    t0 = time.perf_counter()
    rows = {}
    for n in devices:
        rows[n] = _spawn_child(n, requests, swaps)
    digests = {r["digest"] for r in rows.values()}
    base = rows[min(rows)]
    top = rows[max(rows)]
    cores = os.cpu_count() or 1
    row = {
        # headline rate: QPS/chip at the WIDEST mesh (the fleet-scale
        # claim is per-chip throughput holding as chips are added)
        "samples_per_sec_per_chip": top["qps_per_chip"],
        "qps_per_chip": top["qps_per_chip"],
        "parity": "bitwise" if len(digests) == 1 else "MISMATCH",
        "torn_responses": sum(r["torn_responses"] for r in rows.values()),
        "failed_requests": sum(r["failed_requests"]
                               for r in rows.values()),
        "model_swaps": sum(r["model_swaps"] for r in rows.values()),
        "bound": "serving-host",
        "cores": cores,
        # on a host-platform mesh, N virtual chips SHARE the host's
        # cores: dividing a fixed compute roof by N is rig-pessimistic
        # by construction (the SCALING_r06 precedent). The rig-valid
        # signals are the bitwise cross-mesh parity, the swap-storm
        # integrity, and total-QPS RETENTION as the mesh widens
        # (qps_vs_1dev_*: the serving tier's own overhead does not
        # collapse) — per-chip QPS is the physical-TPU reading, where
        # each mesh step adds real silicon.
        "mesh_note": (f"host-platform mesh: virtual devices share "
                      f"{cores} cores; qps/chip divides a fixed "
                      f"compute roof and is rig-pessimistic — the "
                      f"same programs run unchanged over ICI"),
        "dt_s": round(time.perf_counter() - t0, 3),
    }
    for n, r in rows.items():
        row[f"qps_{n}dev"] = r["qps"]
        row[f"qps_per_chip_{n}dev"] = r["qps_per_chip"]
        row[f"p99_ms_{n}dev"] = r["p99_ms"]
        if base["qps"] > 0:
            row[f"qps_vs_1dev_{n}dev"] = round(r["qps"] / base["qps"], 3)
    if base["qps_per_chip"] > 0:
        row["per_chip_scaling"] = round(
            top["qps_per_chip"] / base["qps_per_chip"], 3)
    return row


def smoke() -> int:
    """perf_gate.sh leg: mesh 1 vs mesh 4, bitwise parity + clean swap
    storm. Exit 5 (distinct from lint=1/2, bench_compare=2/3, serve=4)
    so the gate log names the failing leg."""
    bad = []
    try:
        r1 = _spawn_child(1, requests=600, swaps=6)
        r4 = _spawn_child(4, requests=600, swaps=6)
    except (RuntimeError, subprocess.TimeoutExpired) as e:
        print(f"serve-shard smoke FAILED to run: {e}", file=sys.stderr)
        return 5
    if r1["digest"] != r4["digest"]:
        bad.append(f"sharded programs NOT bitwise across meshes: "
                   f"1-dev {r1['digest']} vs 4-dev {r4['digest']}")
    for r in (r1, r4):
        if r["torn_responses"]:
            bad.append(f"{r['devices']}-dev: {r['torn_responses']} TORN "
                       f"responses under sharded swap")
        if r["failed_requests"]:
            bad.append(f"{r['devices']}-dev: {r['failed_requests']} "
                       f"failed requests")
        if r["model_swaps"] < 6:
            bad.append(f"{r['devices']}-dev: only {r['model_swaps']} "
                       f"swaps completed")
    if bad:
        print("serve-shard smoke FAILED:", file=sys.stderr)
        for b in bad:
            print(f"  {b}", file=sys.stderr)
        return 5
    print(f"serve-shard smoke clean: mesh 1 vs 4 bitwise "
          f"({r1['digest']}), {r1['model_swaps']}+{r4['model_swaps']} "
          f"sharded swaps, zero torn")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--devices", default="1,4,8")
    ap.add_argument("--requests", type=int, default=4000)
    ap.add_argument("--swaps", type=int, default=12)
    args = ap.parse_args(argv)
    if args.child:
        n = int(args.devices)
        print(json.dumps(run_child(n, args.requests, args.swaps)))
        return 0
    if args.smoke:
        return smoke()
    devices = tuple(int(d) for d in str(args.devices).split(","))
    row = measure(devices, args.requests, args.swaps)
    print(json.dumps(row, indent=None if args.json else 2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
