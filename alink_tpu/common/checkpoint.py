"""Durable, checksummed checkpoint store — the snapshot format every
recovery path shares.

The reference rides Flink's checkpoint/savepoint machinery (SURVEY §1:
the BSP ``IterativeComQueue`` and the FTRL model stream are fault-tolerant
because the runtime underneath them is). The TPU rebuild has no Flink, so
this module is the substrate: a **zero-extra-dependency** on-disk snapshot
format plus the lifecycle helpers (list / latest / validate / prune) that
``engine/recovery.py`` (superstep snapshots), the FTRL trainer (model
state snapshots) and ``CheckpointSinkStreamOp`` (durable micro-batches)
all build on.

Format (one directory per snapshot)::

    <dir>/ckpt-000000000042/
        manifest.json          # written LAST; a snapshot without a valid
                               # manifest does not exist
        arr_00000.npy          # one .npy per payload array leaf
        arr_00001.npy
        ...

``manifest.json``::

    {"format": "alink_tpu_checkpoint", "version": 1, "tag": 42,
     "created_unix": ..., "meta": {...caller JSON...},
     "structure": <pytree skeleton, leaves as {"t":"leaf","i":k}>,
     "arrays": [{"file": "arr_00000.npy", "shape": [...], "dtype": "...",
                 "bytes": n, "blake2b": "<hex digest of the file>"}, ...]}

Durability contract:

  * **atomic publish** — payload + manifest are written into a hidden
    ``.tmp-*`` sibling, fsynced, then the directory is ``os.rename``d
    into place. Readers only ever see complete snapshots; a crash mid-
    write leaves a ``.tmp-*`` dir that listing ignores and ``prune``
    sweeps.
  * **checksummed load** — every array file's blake2b digest, shape and
    dtype must match the manifest; version must be a known one. A failed
    check raises :class:`CheckpointError`; ``latest_checkpoint`` skips
    invalid snapshots and falls back to the newest valid one.
  * **bitwise round-trip** — payloads are ``.npy`` files written with
    ``allow_pickle=False``; float arrays reload bit-identical, which is
    what makes kill-and-resume parity provable (tests/test_checkpoint.py).

Every successful save/load reports into the MetricsRegistry
(``alink_checkpoint_total`` / ``_bytes_total`` / ``_seconds`` /
``_restore_total``, labelled by ``scope``).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .faults import maybe_crash
from .metrics import get_registry, metrics_enabled
from .tracing import trace_instant

__all__ = [
    "CheckpointError", "FORMAT_NAME", "FORMAT_VERSION",
    "save_checkpoint", "load_checkpoint", "validate_checkpoint",
    "list_checkpoints", "latest_checkpoint", "load_latest_validated",
    "prune_checkpoints", "checkpoint_tag", "read_manifest",
]

FORMAT_NAME = "alink_tpu_checkpoint"
FORMAT_VERSION = 1
MANIFEST = "manifest.json"
_PREFIX = "ckpt-"
_TMP_PREFIX = ".tmp-"


class CheckpointError(RuntimeError):
    """Invalid, corrupted or mismatched snapshot."""


# ---------------------------------------------------------------------------
# pytree <-> (structure json, leaf list)
# ---------------------------------------------------------------------------

def _encode_structure(obj: Any, leaves: List[np.ndarray]) -> Any:
    """JSON skeleton of a payload pytree; array leaves are replaced by
    ``{"t": "leaf", "i": k}`` and collected into ``leaves``. Containers:
    dict (string keys) / list / tuple. Scalars (str/int/float/bool/None)
    stay inline. Anything else is rejected — the format must stay
    readable by any numpy-only process."""
    if isinstance(obj, (np.ndarray, np.generic)) or (
            hasattr(obj, "shape") and hasattr(obj, "dtype")):
        arr = np.asarray(obj)
        if arr.dtype == object:
            raise CheckpointError(
                "checkpoint payload arrays must have a fixed dtype; got an "
                "object array (encode strings as unicode or store them in "
                "meta=)")
        leaves.append(arr)
        return {"t": "leaf", "i": len(leaves) - 1}
    if isinstance(obj, dict):
        for k in obj:
            if not isinstance(k, str):
                raise CheckpointError(
                    f"checkpoint payload dict keys must be str, got "
                    f"{type(k).__name__}")
        return {"t": "dict",
                "v": {k: _encode_structure(v, leaves) for k, v in obj.items()}}
    if isinstance(obj, (list, tuple)):
        return {"t": "list" if isinstance(obj, list) else "tuple",
                "v": [_encode_structure(v, leaves) for v in obj]}
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return {"t": "scalar", "v": obj}
    raise CheckpointError(
        f"unsupported payload node type {type(obj).__name__}; pass arrays, "
        f"dicts, lists, tuples or JSON scalars")


def _decode_structure(node: Any, leaves: List[np.ndarray]) -> Any:
    t = node.get("t") if isinstance(node, dict) else None
    if t == "leaf":
        return leaves[node["i"]]
    if t == "dict":
        return {k: _decode_structure(v, leaves) for k, v in node["v"].items()}
    if t == "list":
        return [_decode_structure(v, leaves) for v in node["v"]]
    if t == "tuple":
        return tuple(_decode_structure(v, leaves) for v in node["v"])
    if t == "scalar":
        return node["v"]
    raise CheckpointError(f"manifest structure: unknown node {node!r}")


def _fsync_dir(path: str) -> None:
    """fsync a directory's metadata so a just-published rename survives
    power loss (no-op on filesystems/platforms that refuse O_RDONLY
    directory fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _digest_file(path: str) -> str:
    h = hashlib.blake2b(digest_size=16)
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def checkpoint_tag(path: str) -> int:
    """Numeric tag of a snapshot directory name (``.../ckpt-42`` -> 42)."""
    base = os.path.basename(os.path.normpath(path))
    if not base.startswith(_PREFIX):
        raise CheckpointError(f"not a checkpoint directory name: {base!r}")
    try:
        return int(base[len(_PREFIX):])
    except ValueError:
        raise CheckpointError(f"non-numeric checkpoint tag in {base!r}")


# ---------------------------------------------------------------------------
# save
# ---------------------------------------------------------------------------

def save_checkpoint(directory: str, tag: int, payload: Any,
                    meta: Optional[Dict[str, Any]] = None, *,
                    scope: str = "default",
                    keep_last: Optional[int] = None) -> str:
    """Atomically persist ``payload`` (a pytree of arrays) as snapshot
    ``ckpt-<tag>`` under ``directory``; returns the published path.

    ``meta`` is caller JSON stored verbatim in the manifest (resume
    validation data: program signatures, batch counters, ...).
    ``keep_last=N`` prunes older snapshots after a successful publish
    (bounded retention; the just-written snapshot always survives).
    """
    t0 = time.perf_counter()
    tag = int(tag)
    os.makedirs(directory, exist_ok=True)
    final = os.path.join(directory, f"{_PREFIX}{tag:012d}")
    tmp = os.path.join(directory,
                       f"{_TMP_PREFIX}{_PREFIX}{tag:012d}-{os.getpid()}")
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        leaves: List[np.ndarray] = []
        structure = _encode_structure(payload, leaves)
        arrays = []
        total_bytes = 0
        for i, arr in enumerate(leaves):
            fname = f"arr_{i:05d}.npy"
            fpath = os.path.join(tmp, fname)
            with open(fpath, "wb") as f:
                np.save(f, arr, allow_pickle=False)
                f.flush()
                os.fsync(f.fileno())
            total_bytes += os.path.getsize(fpath)
            arrays.append({"file": fname, "shape": list(arr.shape),
                           "dtype": str(arr.dtype),
                           "bytes": os.path.getsize(fpath),
                           "blake2b": _digest_file(fpath)})
        manifest = {"format": FORMAT_NAME, "version": FORMAT_VERSION,
                    "tag": tag, "created_unix": time.time(),
                    "meta": meta or {}, "structure": structure,
                    "arrays": arrays}
        mpath = os.path.join(tmp, MANIFEST)
        with open(mpath, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        # the injected-kill point: a crash here must leave no visible
        # snapshot (the .tmp dir is ignored by every reader)
        maybe_crash("ckpt.save")
        if os.path.exists(final):
            # re-publishing a tag (e.g. a retried save): replace the old
            # snapshot; rename-over-directory is not portable, so swap via
            # a doomed name. The window where ``final`` is absent is
            # tolerated because readers fall back to the previous tag.
            doomed = tmp + ".old"
            os.rename(final, doomed)
            os.rename(tmp, final)
            shutil.rmtree(doomed, ignore_errors=True)
        else:
            os.rename(tmp, final)
        # the rename is only durable once the PARENT's metadata is on
        # disk; without this a power cut after 'publish' could resurface
        # with the snapshot entry missing
        _fsync_dir(directory)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if metrics_enabled():
        reg = get_registry()
        lbl = {"scope": scope}
        reg.inc("alink_checkpoint_total", 1, lbl)
        reg.inc("alink_checkpoint_bytes_total", total_bytes, lbl)
        reg.observe("alink_checkpoint_seconds", time.perf_counter() - t0, lbl)
        reg.set_gauge("alink_checkpoint_last_tag", tag, lbl)
    trace_instant("checkpoint.save", cat="ckpt",
                  args={"scope": scope, "tag": tag, "bytes": total_bytes,
                        "seconds": round(time.perf_counter() - t0, 6)})
    if keep_last is not None:
        prune_checkpoints(directory, keep_last)
    return final


# ---------------------------------------------------------------------------
# load / validate
# ---------------------------------------------------------------------------

def read_manifest(path: str) -> Dict[str, Any]:
    """Parse + shallow-validate a snapshot's manifest (no payload reads)."""
    mpath = os.path.join(path, MANIFEST)
    if not os.path.isfile(mpath):
        raise CheckpointError(f"{path}: no {MANIFEST} (incomplete snapshot)")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointError(f"{path}: unreadable manifest: {e}")
    if manifest.get("format") != FORMAT_NAME:
        raise CheckpointError(
            f"{path}: not an {FORMAT_NAME} snapshot "
            f"(format={manifest.get('format')!r})")
    if manifest.get("version") != FORMAT_VERSION:
        raise CheckpointError(
            f"{path}: unsupported snapshot version "
            f"{manifest.get('version')!r} (this build reads "
            f"version {FORMAT_VERSION})")
    return manifest


def validate_checkpoint(path: str) -> Dict[str, Any]:
    """Full integrity check (manifest + every array's digest/shape/dtype);
    returns the manifest. Raises :class:`CheckpointError` on any defect."""
    manifest = read_manifest(path)
    for spec in manifest["arrays"]:
        fpath = os.path.join(path, spec["file"])
        if not os.path.isfile(fpath):
            raise CheckpointError(f"{path}: missing payload {spec['file']}")
        if os.path.getsize(fpath) != spec["bytes"]:
            raise CheckpointError(
                f"{path}: {spec['file']} is {os.path.getsize(fpath)} bytes, "
                f"manifest says {spec['bytes']} (truncated?)")
        digest = _digest_file(fpath)
        if digest != spec["blake2b"]:
            raise CheckpointError(
                f"{path}: {spec['file']} checksum mismatch "
                f"({digest} != manifest {spec['blake2b']})")
    return manifest


def load_checkpoint(path: str, *, scope: str = "default",
                    validate: bool = True) -> Tuple[Any, Dict[str, Any]]:
    """Load one snapshot directory; returns ``(payload, meta)``.

    ``validate=True`` (default) checksums every file before deserializing.
    Arrays additionally verify shape/dtype against the manifest after
    ``np.load`` — a tampered-but-redigested file still cannot smuggle a
    different geometry into a resume.
    """
    manifest = validate_checkpoint(path) if validate else read_manifest(path)
    leaves: List[np.ndarray] = []
    for spec in manifest["arrays"]:
        fpath = os.path.join(path, spec["file"])
        try:
            arr = np.load(fpath, allow_pickle=False)
        except (OSError, ValueError) as e:
            raise CheckpointError(f"{path}: cannot load {spec['file']}: {e}")
        if list(arr.shape) != spec["shape"] or str(arr.dtype) != spec["dtype"]:
            raise CheckpointError(
                f"{path}: {spec['file']} is {arr.shape}/{arr.dtype}, "
                f"manifest says {spec['shape']}/{spec['dtype']}")
        leaves.append(arr)
    payload = _decode_structure(manifest["structure"], leaves)
    if metrics_enabled():
        get_registry().inc("alink_checkpoint_restore_total", 1,
                           {"scope": scope})
    trace_instant("checkpoint.restore", cat="ckpt",
                  args={"scope": scope, "tag": manifest.get("tag")})
    return payload, manifest.get("meta", {})


# ---------------------------------------------------------------------------
# listing / retention
# ---------------------------------------------------------------------------

def list_checkpoints(directory: str) -> List[str]:
    """Published snapshot paths under ``directory``, oldest first.
    In-flight ``.tmp-*`` dirs and foreign files are ignored; validity is
    NOT checked (use ``validate_checkpoint`` / ``latest_checkpoint``)."""
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        if not name.startswith(_PREFIX):
            continue
        path = os.path.join(directory, name)
        if not os.path.isdir(path):
            continue
        try:
            tag = checkpoint_tag(path)
        except CheckpointError:
            continue
        out.append((tag, path))
    return [p for _, p in sorted(out)]


def latest_checkpoint(directory: str, *,
                      validate: bool = True) -> Optional[str]:
    """Newest snapshot path, or None. With ``validate=True`` corrupted /
    incomplete snapshots are skipped (newest VALID wins) — the crash-
    during-write recovery guarantee."""
    for path in reversed(list_checkpoints(directory)):
        if not validate:
            return path
        try:
            validate_checkpoint(path)
            return path
        except CheckpointError:
            continue
    return None


def load_latest_validated(directory: str, expected_signature: Any, *,
                          scope: str = "default",
                          what: str = "program"
                          ) -> Optional[Tuple[Any, Dict[str, Any]]]:
    """Newest valid snapshot's ``(payload, meta)``, refusing a resume
    target whose ``meta["signature"]`` differs from ``expected_signature``
    (raises :class:`CheckpointError`); None when the directory holds no
    valid snapshot. The shared resume entry point: validates checksums
    exactly once (``latest_checkpoint`` already digested the winner)."""
    path = latest_checkpoint(directory)
    if path is None:
        return None
    payload, meta = load_checkpoint(path, scope=scope, validate=False)
    got = meta.get("signature")
    if got != expected_signature:
        raise CheckpointError(
            f"{path}: snapshot belongs to a different {what} "
            f"(signature {got!r} != expected {expected_signature!r}); "
            f"refusing to resume — clear the directory or match the "
            f"configuration")
    return payload, meta


def prune_checkpoints(directory: str, keep_last: int) -> List[str]:
    """Delete all but the newest ``keep_last`` snapshots (plus any stale
    ``.tmp-*`` debris); returns the removed paths."""
    if keep_last < 1:
        raise ValueError(f"keep_last must be >= 1, got {keep_last}")
    removed = []
    ckpts = list_checkpoints(directory)
    for path in ckpts[:-keep_last] if keep_last < len(ckpts) else []:
        shutil.rmtree(path, ignore_errors=True)
        removed.append(path)
    if os.path.isdir(directory):
        for name in os.listdir(directory):
            if name.startswith(_TMP_PREFIX):
                shutil.rmtree(os.path.join(directory, name),
                              ignore_errors=True)
                removed.append(os.path.join(directory, name))
    return removed
