"""Repeatable multi-process use_remote_env test (VERDICT round-2 item 8).

Round 1 verified the jax.distributed Gloo join by hand (commit d426458);
this spawns TWO fresh interpreters that both call ``use_remote_env`` with
the same coordinator, asserts the joined runtime spans both processes'
devices, and runs a BSP AllReduce program on the resulting session so the
cross-process collective path is exercised, not just the handshake.
"""

import os
import socket
import subprocess
import sys

import pytest

_CHILD = r"""
import sys
import numpy as np

coordinator, pid = sys.argv[1], int(sys.argv[2])

from alink_tpu.common.mlenv import use_remote_env
env = use_remote_env(coordinator_address=coordinator, num_processes=2,
                     process_id=pid, parallelism=4)

import jax
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, jax.devices()          # 2 local x 2 procs
assert env.num_workers == 4

# cross-process collective through the engine: psum over the session mesh
import jax.numpy as jnp
from alink_tpu.engine import IterativeComQueue

def stage(ctx):
    ctx.put_obj("total", ctx.all_reduce_sum(ctx.get_obj("x").sum()))

data = np.arange(8, dtype=np.float64)       # same global input on each host
res = (IterativeComQueue(env=env, max_iter=1)
       .init_with_partitioned_data("x", data)
       .add(stage)
       .exec())
total = float(res.get("total"))
assert total == data.sum(), total
print("CHILD_OK", pid, total)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_gloo_join_and_collective(tmp_path):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo_root)
    from bootenv import cpu_mesh_env

    coordinator = f"127.0.0.1:{_free_port()}"
    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    procs = []
    for pid in range(2):
        env = cpu_mesh_env(2)               # 2 virtual CPU devices per proc
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        procs.append(subprocess.Popen(
            [sys.executable, str(script), coordinator, str(pid)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    outs = []
    _CPU_MULTIPROC_UNSUPPORTED = (
        "Multiprocess computations aren't implemented on the CPU backend")
    for p in procs:
        try:
            out, _ = p.communicate(timeout=200)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out.decode())
    if any(_CPU_MULTIPROC_UNSUPPORTED in out for out in outs):
        # This jaxlib build's CPU client refuses to EXECUTE a compiled
        # multi-process program ("Multiprocess computations aren't
        # implemented on the CPU backend", raised only at runtime from
        # the compiled call). The Gloo coordinator join, the 2-process
        # device enumeration, and the session plumbing all succeeded —
        # the asserts before exec() passed in the child — so the failure
        # is an environment capability, not a repo regression. Real
        # multi-host meshes (TPU; jaxlib builds with the CPU
        # collectives) run this path; xfail rather than skip so a
        # jaxlib upgrade that fixes it shows up as XPASS.
        pytest.xfail("jaxlib CPU backend cannot execute multiprocess "
                     "computations (runtime capability of this build); "
                     "gloo join + device enumeration verified up to the "
                     "compiled exec")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"child {pid} failed:\n{out}"
        assert f"CHILD_OK {pid}" in out, out