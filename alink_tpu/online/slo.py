"""End-to-end SLO contract for the online-learning DAG (ISSUE 15).

"The Tail at Scale" discipline applied to the WHOLE loop instead of per
stage: one :class:`SloContract` declares the service-level bounds the
ingest -> train -> hot-swap -> serve -> eval program must hold —

* ``serve_p99_s``        — serving p99 latency bound, evaluated live at
  every eval-window close over the server's rolling latency window;
* ``swap_staleness_s``   — model-swap staleness bound: wall time from a
  model snapshot leaving the trainer to the swap being installed in the
  serving tier (the "how stale can the served model be" clause);
* ``final_window_auc``   — quality floor on the LAST closed eval
  window's AUC (the convergence anchor; VERDICT #7 wants this number
  discriminating, not chance-shaped).

Breaches are TYPED (:class:`SloVerdict`), recorded live (metric
``alink_e2e_slo_breaches_total{slo=}`` + an ``e2e.slo_breach`` trace
instant) and collected on the :class:`~alink_tpu.online.dag.DagReport`;
:meth:`SloContract.final` renders the end-of-run verdict list. A bound
of ``None``/0 disarms its clause — the contract never invents bounds
the operator did not set (``ALINK_TPU_E2E_DAG=1`` opts into the
flag-derived defaults).
"""

from __future__ import annotations

import time
from typing import List, NamedTuple, Optional

from ..common.flags import flag_value
from ..common.metrics import get_registry, metrics_enabled
from ..common.tracing import trace_instant

__all__ = ["SloContract", "SloVerdict", "e2e_dag_enabled", "slo_p99_s",
           "slo_staleness_s", "slo_auc_floor", "e2e_deadline_s"]


def e2e_dag_enabled() -> bool:
    """``ALINK_TPU_E2E_DAG``: arm flag-derived DAG defaults."""
    return bool(flag_value("ALINK_TPU_E2E_DAG"))


def slo_p99_s() -> Optional[float]:
    """``ALINK_TPU_E2E_SLO_P99_MS`` in seconds (None = clause off)."""
    ms = float(flag_value("ALINK_TPU_E2E_SLO_P99_MS"))
    return ms / 1e3 if ms > 0 else None


def slo_staleness_s() -> Optional[float]:
    """``ALINK_TPU_E2E_SLO_STALENESS_MS`` in seconds (None = off)."""
    ms = float(flag_value("ALINK_TPU_E2E_SLO_STALENESS_MS"))
    return ms / 1e3 if ms > 0 else None


def slo_auc_floor() -> Optional[float]:
    """``ALINK_TPU_E2E_SLO_AUC`` (None = clause off)."""
    v = float(flag_value("ALINK_TPU_E2E_SLO_AUC"))
    return v if v > 0 else None


def e2e_deadline_s() -> Optional[float]:
    """``ALINK_TPU_E2E_DEADLINE_MS`` in seconds (None = no deadline)."""
    ms = float(flag_value("ALINK_TPU_E2E_DEADLINE_MS"))
    return ms / 1e3 if ms > 0 else None


class SloVerdict(NamedTuple):
    """One typed SLO clause verdict: ``slo`` names the clause
    (``serve_p99`` | ``swap_staleness`` | ``final_window_auc``),
    ``ok`` whether the observation honored the bound, ``observed``/
    ``bound`` the numbers (seconds for the latency clauses), and
    ``detail`` a human sentence naming the phase/window."""
    slo: str
    ok: bool
    observed: Optional[float]
    bound: float
    detail: str

    def to_dict(self) -> dict:
        return {"slo": self.slo, "ok": bool(self.ok),
                "observed": self.observed, "bound": self.bound,
                "detail": self.detail}


class SloContract:
    """Declarative end-to-end SLO bounds + live breach recording.

    Construct explicitly, or :meth:`from_flags` under
    ``ALINK_TPU_E2E_DAG=1``. ``observe_*`` methods are called by the
    DAG at window closes / swaps; every breach lands in
    :attr:`breaches` exactly once per (clause, context) so a sustained
    storm reads as one typed event per window, not a counter melt."""

    def __init__(self, serve_p99_s: Optional[float] = None,
                 swap_staleness_s: Optional[float] = None,
                 final_window_auc: Optional[float] = None,
                 name: str = "online"):
        self.serve_p99_s = serve_p99_s
        self.swap_staleness_s = swap_staleness_s
        self.final_window_auc = final_window_auc
        self.name = name
        self.breaches: List[SloVerdict] = []

    @classmethod
    def from_flags(cls, name: str = "online") -> "SloContract":
        """The ``ALINK_TPU_E2E_SLO_*`` flag-derived contract."""
        return cls(serve_p99_s=slo_p99_s(),
                   swap_staleness_s=slo_staleness_s(),
                   final_window_auc=slo_auc_floor(), name=name)

    def armed(self) -> bool:
        return any(b is not None for b in (self.serve_p99_s,
                                           self.swap_staleness_s,
                                           self.final_window_auc))

    # -- live observation (the DAG calls these) ---------------------------
    def _breach(self, verdict: SloVerdict) -> None:
        self.breaches.append(verdict)
        trace_instant("e2e.slo_breach", cat="e2e",
                      args={"slo": verdict.slo,
                            "observed": verdict.observed,
                            "bound": verdict.bound,
                            "detail": verdict.detail})
        if metrics_enabled():
            get_registry().inc("alink_e2e_slo_breaches_total", 1,
                               {"dag": self.name, "slo": verdict.slo})

    def observe_p99(self, p99_s: Optional[float],
                    window: int) -> Optional[SloVerdict]:
        """Live p99 check at an eval-window close; returns the typed
        breach (already recorded) or ``None``."""
        if self.serve_p99_s is None or p99_s is None:
            return None
        if p99_s <= self.serve_p99_s:
            return None
        v = SloVerdict("serve_p99", False, float(p99_s),
                       float(self.serve_p99_s),
                       f"window {window}: serving p99 "
                       f"{p99_s * 1e3:.1f} ms > bound "
                       f"{self.serve_p99_s * 1e3:.1f} ms")
        self._breach(v)
        return v

    def observe_swap(self, staleness_s: float,
                     version: int) -> Optional[SloVerdict]:
        """Per-swap staleness check (emission -> installed)."""
        if self.swap_staleness_s is None \
                or staleness_s <= self.swap_staleness_s:
            return None
        v = SloVerdict("swap_staleness", False, float(staleness_s),
                       float(self.swap_staleness_s),
                       f"swap to version {version} took "
                       f"{staleness_s * 1e3:.1f} ms > bound "
                       f"{self.swap_staleness_s * 1e3:.1f} ms")
        self._breach(v)
        return v

    # -- the end-of-run verdict -------------------------------------------
    def final(self, p99_s: Optional[float],
              max_staleness_s: Optional[float],
              final_auc: Optional[float]) -> List[SloVerdict]:
        """The whole-run verdict list — one typed entry per ARMED
        clause, ``ok`` reflecting the run's worst observation (live
        breaches already recorded separately in :attr:`breaches`)."""
        out: List[SloVerdict] = []
        if self.serve_p99_s is not None:
            ok = p99_s is not None and p99_s <= self.serve_p99_s
            out.append(SloVerdict(
                "serve_p99", ok, p99_s, float(self.serve_p99_s),
                f"run p99 {p99_s * 1e3:.1f} ms vs bound "
                f"{self.serve_p99_s * 1e3:.1f} ms"
                if p99_s is not None else "no latency samples"))
        if self.swap_staleness_s is not None:
            ok = (max_staleness_s is None
                  or max_staleness_s <= self.swap_staleness_s)
            out.append(SloVerdict(
                "swap_staleness", ok, max_staleness_s,
                float(self.swap_staleness_s),
                f"max swap staleness "
                f"{(max_staleness_s or 0.0) * 1e3:.1f} ms vs bound "
                f"{self.swap_staleness_s * 1e3:.1f} ms"))
        if self.final_window_auc is not None:
            ok = final_auc is not None \
                and final_auc >= self.final_window_auc
            out.append(SloVerdict(
                "final_window_auc", ok, final_auc,
                float(self.final_window_auc),
                f"final-window AUC "
                f"{final_auc if final_auc is not None else 'n/a'} vs "
                f"floor {self.final_window_auc}"))
        return out


class SwapStalenessTracker:
    """Measures the emission->installed wall time of every model swap.

    The DAG's feeder callback opens a sample when a snapshot leaves the
    trainer (``mark_emitted``) and closes it when the swap lands
    (``mark_installed``); the max/mean ride the report and the
    ``alink_e2e_swap_staleness_seconds`` gauge."""

    def __init__(self, contract: Optional[SloContract] = None,
                 name: str = "online"):
        self.contract = contract
        self.name = name
        self.samples: List[float] = []
        self._open: Optional[float] = None

    def mark_emitted(self) -> None:
        self._open = time.perf_counter()

    def mark_installed(self, version: int) -> float:
        t0 = self._open if self._open is not None else time.perf_counter()
        dt = time.perf_counter() - t0
        self._open = None
        self.samples.append(dt)
        if metrics_enabled():
            get_registry().set_gauge("alink_e2e_swap_staleness_seconds",
                                     dt, {"dag": self.name})
        if self.contract is not None:
            self.contract.observe_swap(dt, version)
        return dt

    @property
    def max_s(self) -> Optional[float]:
        return max(self.samples) if self.samples else None

    @property
    def mean_s(self) -> Optional[float]:
        return (sum(self.samples) / len(self.samples)
                if self.samples else None)
