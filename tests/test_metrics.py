"""Runtime telemetry subsystem (common/metrics.py) + instrumented hot paths.

Covers the MetricsRegistry contract (counter/gauge/histogram semantics,
label cardinality, JSONL round-trip, Prometheus rendering), the
ALINK_TPU_METRICS=0 guard, StepTimer thread-safety + registry mirroring,
and the end-to-end engine assertion: one IterativeComQueue.exec() records
supersteps, per-collective traffic and program-cache hits, the dump renders
through tools/run_report.py, and metrics add NO host callback to the
compiled program.
"""

import importlib.util
import json
import os
import threading

import numpy as np
import pytest

from alink_tpu.common.metrics import (DEFAULT_BUCKETS, MetricsRegistry,
                                      env_flag, get_registry,
                                      metrics_enabled, set_registry)
from alink_tpu.common.profiling import StepTimer, step_log_enabled

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def fresh_registry():
    """Isolate the process registry per test (engine/ops report into it)."""
    reg = MetricsRegistry()
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

class TestCounterGaugeHistogram:
    def test_counter_accumulates(self):
        reg = MetricsRegistry()
        reg.inc("c", 1)
        reg.inc("c", 2.5)
        assert reg.value("c") == 3.5
        # labelled series are independent
        reg.inc("c", 7, {"k": "a"})
        assert reg.value("c", {"k": "a"}) == 7
        assert reg.value("c") == 3.5

    def test_counter_rejects_negative(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.inc("c", -1)

    def test_gauge_sets_last_value(self):
        reg = MetricsRegistry()
        reg.set_gauge("g", 5)
        reg.set_gauge("g", 2)
        assert reg.value("g") == 2

    def test_kind_conflict_fails_loudly(self):
        reg = MetricsRegistry()
        reg.inc("m")
        with pytest.raises(TypeError):
            reg.set_gauge("m", 1)
        with pytest.raises(TypeError):
            reg.observe("m", 1.0)

    def test_histogram_buckets_cumulative_semantics(self):
        reg = MetricsRegistry()
        fam = reg.histogram("h", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.1, 0.5, 5.0, 50.0):
            fam.observe(v)
        (labels, s), = fam.series()
        assert labels == {}
        # le=0.1 gets 0.05 AND the boundary value 0.1; +Inf gets 50.0
        assert s.counts == [2, 1, 1, 1]
        assert s.count == 5 and abs(s.sum - 55.65) < 1e-9

    def test_histogram_bucket_validation(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.histogram("bad", buckets=(1.0, 0.5))
        reg.histogram("h2", buckets=(1.0, 2.0))
        with pytest.raises(ValueError):  # conflicting re-registration
            reg.histogram("h2", buckets=(1.0, 3.0))

    def test_value_reads_never_create_series(self):
        reg = MetricsRegistry()
        assert reg.value("missing", {"a": "b"}) == 0.0
        assert reg.snapshot() == []


class TestLabelCardinality:
    def test_distinct_label_sets_distinct_series(self):
        reg = MetricsRegistry()
        reg.inc("c", 1, {"op": "A"})
        reg.inc("c", 2, {"op": "B"})
        reg.inc("c", 3, {"op": "A", "x": "1"})
        got = {tuple(sorted(l.items())): s.value
               for l, s in reg.counter("c").series()}
        assert got == {(("op", "A"),): 1, (("op", "B"),): 2,
                       (("op", "A"), ("x", "1")): 3}

    def test_cardinality_cap_folds_into_overflow(self):
        reg = MetricsRegistry(max_series_per_metric=4)
        for i in range(10):
            reg.inc("c", 1, {"id": str(i)})  # an id leaking into a label
        fam = reg.counter("c")
        series = fam.series()
        assert len(series) == 5  # 4 real + 1 overflow
        assert reg.value("c", {"alink_overflow": "true"}) == 6
        assert reg._dropped_series == 6

    def test_cardinality_overflow_warns_once_per_metric(self):
        import warnings
        reg = MetricsRegistry(max_series_per_metric=2)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            for i in range(50):               # 48 overflowing samples...
                reg.inc("hot", 1, {"id": str(i)})
            for i in range(10):               # second metric overflows too
                reg.inc("hot2", 1, {"id": str(i)})
        got = [x for x in w if issubclass(x.category, RuntimeWarning)]
        # ...but exactly ONE warning per metric NAME, not per sample
        assert len(got) == 2
        assert "'hot'" in str(got[0].message)
        assert "'hot2'" in str(got[1].message)
        # the fold-in behaviour is unchanged
        assert reg.value("hot", {"alink_overflow": "true"}) == 48


class TestExporters:
    def _populated(self):
        reg = MetricsRegistry()
        reg.inc("alink_requests_total", 3, {"route": "/fit"})
        reg.set_gauge("alink_depth", 2.5)
        reg.observe("alink_latency_seconds", 0.02, {"op": "X"},
                    buckets=(0.01, 0.1))
        reg.observe("alink_latency_seconds", 0.5, {"op": "X"})
        return reg

    def test_jsonl_round_trip(self, tmp_path):
        reg = self._populated()
        p = reg.dump(str(tmp_path / "run.jsonl"))
        # every line is one JSON object; first is the meta record
        lines = [json.loads(l) for l in open(p) if l.strip()]
        assert lines[0]["kind"] == "meta"
        assert lines[0]["format"] == "alink_tpu_metrics_v1"
        loaded = MetricsRegistry.load(p)
        assert loaded.snapshot() == reg.snapshot()
        # and a dump of the loaded registry is identical content
        p2 = loaded.dump(str(tmp_path / "run2.jsonl"))
        assert ([json.loads(l) for l in open(p2)][1:]
                == [json.loads(l) for l in open(p)][1:])

    def test_prometheus_text(self):
        reg = self._populated()
        txt = reg.render_text()
        assert '# TYPE alink_requests_total counter' in txt
        assert 'alink_requests_total{route="/fit"} 3.0' in txt
        assert '# TYPE alink_depth gauge' in txt
        assert 'alink_depth 2.5' in txt
        # histogram: cumulative buckets + implicit +Inf + sum/count
        assert 'alink_latency_seconds_bucket{op="X",le="0.01"} 0' in txt
        assert 'alink_latency_seconds_bucket{op="X",le="0.1"} 1' in txt
        assert 'alink_latency_seconds_bucket{op="X",le="+Inf"} 2' in txt
        assert 'alink_latency_seconds_count{op="X"} 2' in txt

    def test_prometheus_label_escaping(self):
        reg = MetricsRegistry()
        reg.inc("c", 1, {"q": 'say "hi"\nthere'})
        txt = reg.render_text()
        assert r'q="say \"hi\"\nthere"' in txt


# ---------------------------------------------------------------------------
# env flags + StepTimer
# ---------------------------------------------------------------------------

class TestEnvFlags:
    @pytest.mark.parametrize("val,expect", [
        ("0", False), ("false", False), ("False", False), ("off", False),
        ("OFF", False), ("no", False), ("", False),
        ("1", True), ("true", True), ("on", True), ("anything", True)])
    def test_step_log_flag_parsing(self, monkeypatch, val, expect):
        monkeypatch.setenv("ALINK_TPU_STEP_LOG", val)
        assert step_log_enabled() is expect

    def test_step_log_default_off(self, monkeypatch):
        monkeypatch.delenv("ALINK_TPU_STEP_LOG", raising=False)
        assert step_log_enabled() is False

    def test_metrics_default_on_and_disable(self, monkeypatch):
        monkeypatch.delenv("ALINK_TPU_METRICS", raising=False)
        assert metrics_enabled() is True
        for off in ("0", "false", "off"):
            monkeypatch.setenv("ALINK_TPU_METRICS", off)
            assert metrics_enabled() is False

    def test_env_flag_default(self, monkeypatch):
        monkeypatch.delenv("ALINK_X", raising=False)
        assert env_flag("ALINK_X", default=True) is True
        assert env_flag("ALINK_X", default=False) is False


class TestStepTimer:
    def test_thread_safe_concurrent_spans(self, fresh_registry):
        t = StepTimer()
        n_threads, n_spans = 8, 200

        def work(i):
            for _ in range(n_spans):
                with t.span("shared"):
                    pass
                with t.span(f"own{i}"):
                    pass

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(n_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        rows = {name: count for name, count, _, _ in t.report()}
        assert rows["shared"] == n_threads * n_spans
        for i in range(n_threads):
            assert rows[f"own{i}"] == n_spans
        # and the registry mirror saw every span exit
        fam = fresh_registry.histogram(StepTimer.METRIC)
        total = sum(s.count for _, s in fam.series())
        assert total == 2 * n_threads * n_spans

    def test_span_labels_passthrough(self, fresh_registry):
        t = StepTimer()
        with t.span("fit", labels={"algo": "kmeans"}):
            pass
        fam = fresh_registry.histogram(StepTimer.METRIC)
        (labels, s), = fam.series()
        assert labels == {"span": "fit", "algo": "kmeans"} and s.count == 1

    def test_mirror_respects_metrics_guard(self, fresh_registry, monkeypatch):
        monkeypatch.setenv("ALINK_TPU_METRICS", "0")
        t = StepTimer()
        with t.span("fit"):
            pass
        assert t.report()[0][1] == 1          # host timer still accumulates
        assert fresh_registry.snapshot() == []  # registry untouched

    def test_mirror_off(self, fresh_registry):
        t = StepTimer(mirror=False)
        with t.span("fit"):
            pass
        assert fresh_registry.snapshot() == []


# ---------------------------------------------------------------------------
# end-to-end: the instrumented engine
# ---------------------------------------------------------------------------

def _make_queue(key=None, max_iter=4):
    import jax.numpy as jnp

    from alink_tpu.engine.communication import AllReduce
    from alink_tpu.engine.comqueue import IterativeComQueue

    X = np.arange(64.0).reshape(32, 2)

    def stage(ctx):
        if ctx.is_init_step:
            ctx.put_obj("s", jnp.zeros(()))
        ctx.put_obj("s", ctx.get_obj("X").sum())

    q = (IterativeComQueue(max_iter=max_iter)
         .init_with_partitioned_data("X", X)
         .add(stage)
         .add(AllReduce("s")))
    if key is not None:
        q.set_program_key(key)
    return q


class TestEngineTelemetry:
    def test_exec_records_supersteps_collectives_and_cache(
            self, fresh_registry, tmp_path):
        reg = fresh_registry
        key = ("test_metrics_e2e", os.urandom(6).hex())
        q = _make_queue(key=key, max_iter=4)
        r = q.exec()
        steps = r.step_count
        assert steps == 4
        assert reg.value("alink_comqueue_execs_total") == 1
        assert reg.value("alink_comqueue_supersteps_total") == steps
        assert reg.value("alink_comqueue_program_cache_total",
                         {"result": "miss"}) == 1
        # one AllReduce per superstep; logical bytes = scalar payload
        # summed over the 8 workers, per superstep
        ar = {"collective": "AllReduce"}
        assert reg.value("alink_collective_calls_total", ar) == steps
        itemsize = np.asarray(r.get("s")).dtype.itemsize
        assert reg.value("alink_collective_logical_bytes_total", ar) \
            == steps * 8 * itemsize

        # re-exec: program-cache HIT, and the cached program's collective
        # manifest still attributes traffic (nothing is re-traced)
        q2 = _make_queue(key=key, max_iter=4)
        q2.exec()
        assert reg.value("alink_comqueue_program_cache_total",
                         {"result": "hit"}) == 1
        assert reg.value("alink_collective_calls_total", ar) == 2 * steps
        assert reg.value("alink_comqueue_execs_total") == 2
        assert reg.value("alink_comqueue_supersteps_total") == 2 * steps

        # per-stage wall time (StepTimer spans mirrored into the registry)
        fam = reg.histogram(StepTimer.METRIC)
        spans = {l.get("span") for l, _ in fam.series()}
        assert "comqueue.execute" in spans and "comqueue.prepare" in spans

        # the dump is a complete run report: JSONL with supersteps,
        # collective bytes, cache hits and stage wall time all present
        p = reg.dump(str(tmp_path / "run.jsonl"))
        names = {json.loads(l)["name"] for l in open(p)
                 if json.loads(l).get("kind") != "meta"}
        assert {"alink_comqueue_supersteps_total",
                "alink_collective_calls_total",
                "alink_collective_logical_bytes_total",
                "alink_comqueue_program_cache_total",
                StepTimer.METRIC} <= names

    def test_init_only_collective_charged_once(self, fresh_registry):
        """A collective that runs only on the init pass (the reference
        stepNo==1 idiom) executes once per run — not once per superstep;
        a body collective executes steps-1 times plus the init pass."""
        import jax.numpy as jnp

        from alink_tpu.engine.comqueue import IterativeComQueue

        def stage(ctx):
            X = ctx.get_obj("X")
            if ctx.is_init_step:
                ctx.put_obj("init_sum", ctx.all_reduce_sum(X.sum()))
                ctx.put_obj("s", jnp.zeros(()))
            ctx.put_obj("s", X.sum())

        q = (IterativeComQueue(max_iter=5)
             .init_with_partitioned_data("X", np.ones((16, 2))).add(stage))
        r = q.exec()
        assert r.step_count == 5
        assert fresh_registry.value("alink_collective_calls_total",
                                    {"collective": "InlineAllReduce"}) == 1

    def test_cached_program_attribution_tracks_shapes(self, fresh_registry):
        """One cached program serves several traced shapes; each exec's
        collective bytes must come from ITS shape's manifest, including
        when jit reuses an earlier trace on a later cache hit."""
        reg = fresh_registry
        key = ("test_metrics_shapes", os.urandom(6).hex())
        ar = {"collective": "AllReduce"}

        def run(rows):
            from alink_tpu.engine.communication import AllReduce
            from alink_tpu.engine.comqueue import IterativeComQueue

            def stage(ctx):
                X = ctx.get_obj("X")
                # per-row payload: the AllReduce bytes SCALE with the
                # input shape, so stale-manifest attribution would show
                ctx.put_obj("v", X.sum(1))

            return (IterativeComQueue(max_iter=2)
                    .init_with_partitioned_data("X", np.ones((rows, 2)))
                    .add(stage).add(AllReduce("v"))
                    .set_program_key(key).exec())

        itemsize = np.asarray(run(64).get("v")).dtype.itemsize

        def expect(rows):                      # 2 supersteps x 8 workers
            return 2 * 8 * (rows // 8) * itemsize

        b1 = reg.value("alink_collective_logical_bytes_total", ar)
        assert b1 == expect(64)
        run(128)                               # cache hit, NEW trace
        b2 = reg.value("alink_collective_logical_bytes_total", ar)
        assert b2 - b1 == expect(128)
        run(64)                                # cache hit, REUSED old trace
        b3 = reg.value("alink_collective_logical_bytes_total", ar)
        assert b3 - b2 == expect(64)
        assert reg.value("alink_comqueue_program_cache_total",
                         {"result": "hit"}) == 2

    def test_run_report_renders_dump(self, fresh_registry, tmp_path, capsys):
        key = ("test_metrics_report", os.urandom(6).hex())
        _make_queue(key=key).exec()
        p = fresh_registry.dump(str(tmp_path / "run.jsonl"))

        spec = importlib.util.spec_from_file_location(
            "run_report", os.path.join(ROOT, "tools", "run_report.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main([p]) == 0
        out = capsys.readouterr().out
        assert "Run summary" in out and "AllReduce" in out
        assert "supersteps" in out and "comqueue.execute" in out
        assert mod.main([p, "--prom"]) == 0
        assert "# TYPE alink_comqueue_supersteps_total counter" \
            in capsys.readouterr().out

    def test_metrics_disabled_skips_registry_updates(
            self, fresh_registry, monkeypatch):
        monkeypatch.setenv("ALINK_TPU_METRICS", "0")
        r = _make_queue().exec()
        assert r.step_count == 4          # the run itself is unaffected
        assert fresh_registry.snapshot() == []

    def test_no_host_callback_in_lowered_hlo(self, fresh_registry,
                                             monkeypatch):
        """Metrics-on must not change the compiled program: collective
        accounting happens at trace time on the host, so the lowered HLO
        contains no callback custom-calls."""
        monkeypatch.setenv("ALINK_TPU_METRICS", "1")
        monkeypatch.delenv("ALINK_TPU_STEP_LOG", raising=False)
        txt = _make_queue().lowered().as_text().lower()
        assert "callback" not in txt
        assert "outfeed" not in txt


# ---------------------------------------------------------------------------
# instrumented operator layers
# ---------------------------------------------------------------------------

class TestOperatorTelemetry:
    def test_batch_link_records_time_and_rows(self, fresh_registry):
        from alink_tpu.common.mtable import MTable
        from alink_tpu.operator.base import BatchOperator
        from alink_tpu.operator.batch.sql import SelectBatchOp

        src = BatchOperator.from_table(
            MTable({"a": np.arange(10.0), "b": np.arange(10.0)}))
        out = SelectBatchOp(clause="a").link_from(src)
        assert out.get_output_table().num_rows == 10
        reg = fresh_registry
        lbl = {"op": "SelectBatchOp"}
        assert reg.value("alink_batch_rows_in_total", lbl) == 10
        assert reg.value("alink_batch_rows_out_total", lbl) == 10
        fam = reg.histogram("alink_batch_op_seconds")
        assert any(l == lbl and s.count == 1 for l, s in fam.series())

    def test_stream_transform_records_batches(self, fresh_registry):
        from alink_tpu.common.mtable import MTable
        from alink_tpu.operator.stream.source.sources import MemSourceStreamOp
        from alink_tpu.operator.stream.sql import SelectStreamOp

        n, bs = 40, 8
        src = MemSourceStreamOp(MTable({"a": np.arange(float(n)),
                                        "b": np.arange(float(n))}),
                                batch_size=bs)
        sel = SelectStreamOp(clause="a").link_from(src)
        total = sum(mt.num_rows for mt in sel.micro_batches())
        assert total == n
        reg = fresh_registry
        lbl = {"op": "SelectStreamOp"}
        assert reg.value("alink_stream_batches_total", lbl) == n // bs
        assert reg.value("alink_stream_rows_total", lbl) == n
        fam = reg.histogram("alink_stream_batch_seconds")
        assert any(l == lbl and s.count == n // bs for l, s in fam.series())

    def test_ftrl_collectives_charged_per_micro_batch(self, fresh_registry):
        """The FTRL step programs are jit-cached, so their margin-psum
        manifest records fire once per COMPILE; the drain loop must
        replay each program's captured manifest per micro-batch, or a
        long drain under-counts its AllReduce traffic by the batch
        count (communication.record_manifest / ftrl._step_manifest)."""
        from alink_tpu.common.mtable import MTable
        from alink_tpu.operator.batch.source import MemSourceBatchOp
        from alink_tpu.operator.batch.classification import (
            LogisticRegressionTrainBatchOp)
        from alink_tpu.operator.stream.source.sources import MemSourceStreamOp
        from alink_tpu.operator.stream.onlinelearning.ftrl import (
            FtrlTrainStreamOp)

        rng = np.random.RandomState(3)
        n, bs = 96, 16
        X = rng.randn(n, 3)
        y = (X @ np.array([1.0, -1.0, 0.5]) > 0).astype(np.int64)
        table = MTable({"f0": X[:, 0], "f1": X[:, 1], "f2": X[:, 2],
                        "label": y})
        warm = LogisticRegressionTrainBatchOp(
            feature_cols=["f0", "f1", "f2"], label_col="label",
            max_iter=2).link_from(MemSourceBatchOp(table.first_n(32)))
        warm.get_output_table()          # force the warm train NOW: its
        reg = fresh_registry             # engine collectives must not
        ar = {"collective": "AllReduce"}  # pollute the drain's delta
        base = reg.value("alink_collective_calls_total", ar)
        ftrl = FtrlTrainStreamOp(
            warm, label_col="label", feature_cols=["f0", "f1", "f2"],
            alpha=0.5, time_interval=1e9).link_from(
            MemSourceStreamOp(table, batch_size=bs))
        assert len(list(ftrl.micro_batches())) >= 1
        # ONE margin AllReduce site per step program, executed once per
        # micro-batch: calls count executed batches, not compiles
        assert reg.value("alink_collective_calls_total", ar) - base \
            == n // bs
        assert reg.value("alink_collective_logical_bytes_total", ar) > 0

    def test_operator_paths_respect_guard(self, fresh_registry, monkeypatch):
        monkeypatch.setenv("ALINK_TPU_METRICS", "off")
        from alink_tpu.common.mtable import MTable
        from alink_tpu.operator.base import BatchOperator
        from alink_tpu.operator.batch.sql import SelectBatchOp
        from alink_tpu.operator.stream.source.sources import MemSourceStreamOp
        from alink_tpu.operator.stream.sql import SelectStreamOp

        src = BatchOperator.from_table(MTable({"a": np.arange(4.0)}))
        SelectBatchOp(clause="a").link_from(src)
        s = SelectStreamOp(clause="a").link_from(
            MemSourceStreamOp(MTable({"a": np.arange(4.0)}), batch_size=2))
        assert sum(mt.num_rows for mt in s.micro_batches()) == 4
        assert fresh_registry.snapshot() == []
