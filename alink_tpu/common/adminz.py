"""Live operations plane — in-process admin HTTP endpoint (ISSUE 16).

Every observability layer before this one was post-hoc: JSONL dumps
(``MetricsRegistry.dump``), trace exports (``Tracer.to_chrome``) and
verdict CLIs (``tools/doctor.py``) read artifacts AFTER the process
exits. The long-lived processes this repo now ships — the supervised
:class:`~alink_tpu.online.dag.OnlineDag` and the hot-swap
:class:`~alink_tpu.serving.server.PredictServer` — are *operated*, not
just benchmarked, and need a live plane. This module is it:

* :class:`AdminServer` — a **stdlib-only** ``ThreadingHTTPServer``
  serving, from the LIVE process state (nothing is copied or dumped):

  ========== ==========================================================
  path        serves
  ========== ==========================================================
  /metrics    Prometheus exposition text straight from the live
              ``MetricsRegistry`` (``render_text()`` — the PR-1
              renderer, unchanged)
  /varz       the same registry as JSON records (``snapshot()`` shape,
              meta record first) — ``tools/doctor.py --url`` and
              ``tools/fleetz.py`` consume this without a prom parser
  /healthz    liveness: 200 while every registered
              :class:`ReadinessSource` reports healthy, else 503
  /readyz     readiness: 200 while every source reports ready AND no
              critical SLO burn is active, else 503
  /statusz    build info, every resolved ``FlagRegistry`` value, and
              the registered status sections (program-cache sizes,
              model-swap history, live SLO clause + burn states)
  /tracez     a bounded snapshot of the PR-3 flight-recorder ring
              (``?trace_id=`` narrows to one request's events)
  /requestz   recent Layer-6 request timelines (admission → queue →
              coalesce → dispatch → device → decode) plus everything
              in flight; ``?trace_id=`` / ``?tenant=`` filter
  /compilez   the Layer-7 compile ledger: per-cache hit/miss/eviction
              counters, recent compile events with the structural diff
              vs the previous plan (the changed dimension, e.g.
              ``ALINK_TPU_SERVE_DTYPE f32→int8``), cold-start
              time-to-first-program per subsystem, and recompile-storm
              state; ``?n=`` bounds the event list
  ========== ==========================================================

* the :class:`ReadinessSource` contract — components plug their REAL
  state in: a readiness callable returns a dict with at least
  ``{"ready": bool}`` (optional ``"healthy"`` defaults to ``ready``;
  everything else is detail rendered verbatim). A callable that raises
  reports as unready with the error attached — a crashed probe must
  degrade the verdict, never 500 the endpoint.

* a refcounted process-wide instance (:func:`acquire_admin` /
  :func:`release_admin`): ``ALINK_TPU_ADMIN_PORT`` armed, the first
  component to start (an ``OnlineDag.run``, a ``PredictServer``)
  brings the endpoint up and the last one down — the endpoint's
  lifetime IS the components' lifetime.

Zero-compiled-ops discipline (the PR 3/4/8 contract): the server only
*reads* host-side state; no flag here is consulted at trace time, and
lowered HLO + program-cache keys are byte-identical with the plane on
or off (``tests/test_adminz.py`` pins it).
"""

from __future__ import annotations

import json
import sys
import threading
import time
import warnings
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .flags import FLAGS, flag_value
from .metrics import get_registry, metrics_enabled

__all__ = [
    "AdminServer", "acquire_admin", "release_admin", "get_admin",
    "admin_enabled", "admin_port", "admin_host", "admin_tracez_events",
    "admin_requestz_entries",
]


def admin_port() -> int:
    """``ALINK_TPU_ADMIN_PORT``: 0 = plane off, -1 = ephemeral port,
    otherwise the fixed port to bind."""
    return int(flag_value("ALINK_TPU_ADMIN_PORT"))


def admin_host() -> str:
    """``ALINK_TPU_ADMIN_HOST``: bind address (loopback default)."""
    return str(flag_value("ALINK_TPU_ADMIN_HOST"))


def admin_tracez_events() -> int:
    """``ALINK_TPU_ADMIN_TRACEZ``: max events per /tracez response."""
    return int(flag_value("ALINK_TPU_ADMIN_TRACEZ"))


def admin_requestz_entries() -> int:
    """``ALINK_TPU_ADMIN_REQUESTZ``: max request timelines per
    /requestz response."""
    return int(flag_value("ALINK_TPU_ADMIN_REQUESTZ"))


def admin_enabled() -> bool:
    """Whether the admin plane is armed (port flag != 0)."""
    return admin_port() != 0


def _json_safe(v: Any) -> Any:
    """Best-effort JSON coercion for status payloads — a status section
    returning a non-serializable value must degrade to its repr, never
    500 the endpoint."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple, set, frozenset)):
        return [_json_safe(x) for x in v]
    return repr(v)


class _Handler(BaseHTTPRequestHandler):
    server_version = "alink-adminz/1"

    # the admin plane must never spam stderr per scrape
    def log_message(self, *a) -> None:  # pragma: no cover - silencer
        pass

    def do_GET(self) -> None:
        admin: "AdminServer" = self.server.admin  # type: ignore[attr-defined]
        t0 = time.perf_counter()
        parsed = urlparse(self.path)
        path = parsed.path.rstrip("/") or "/"
        try:
            if path == "/":
                code, ctype, body = 200, "text/plain; charset=utf-8", \
                    admin._index()
            elif path == "/metrics":
                code, ctype, body = 200, \
                    "text/plain; version=0.0.4; charset=utf-8", \
                    get_registry().render_text()
            elif path == "/varz":
                code, ctype, body = 200, "application/json", \
                    json.dumps(admin._varz())
            elif path == "/healthz":
                ok, doc = admin.health()
                code, ctype, body = (200 if ok else 503), \
                    "application/json", json.dumps(doc)
            elif path == "/readyz":
                ok, doc = admin.readiness()
                code, ctype, body = (200 if ok else 503), \
                    "application/json", json.dumps(doc)
            elif path == "/statusz":
                code, ctype, body = 200, "application/json", \
                    json.dumps(_json_safe(admin.statusz()))
            elif path == "/tracez":
                q = parse_qs(parsed.query)
                try:
                    n = int(q["n"][0]) if "n" in q else None
                except (TypeError, ValueError):
                    n = None
                trace_id = q["trace_id"][0] if "trace_id" in q else None
                code, ctype, body = 200, "application/json", \
                    json.dumps(_json_safe(admin._tracez(n, trace_id)))
            elif path == "/requestz":
                q = parse_qs(parsed.query)
                try:
                    n = int(q["n"][0]) if "n" in q else None
                except (TypeError, ValueError):
                    n = None
                trace_id = q["trace_id"][0] if "trace_id" in q else None
                tenant = q["tenant"][0] if "tenant" in q else None
                code, ctype, body = 200, "application/json", \
                    json.dumps(_json_safe(
                        admin._requestz(n, trace_id, tenant)))
            elif path == "/compilez":
                from . import compileledger
                q = parse_qs(parsed.query)
                try:
                    n = int(q["n"][0]) if "n" in q else None
                except (TypeError, ValueError):
                    n = None
                code, ctype, body = 200, "application/json", \
                    json.dumps(_json_safe(compileledger.compilez_doc(n)))
            else:
                code, ctype, body = 404, "text/plain; charset=utf-8", \
                    f"404: unknown admin path {path!r}\n" + admin._index()
        except Exception as e:  # a handler bug must answer, not hang
            code, ctype = 500, "text/plain; charset=utf-8"
            body = f"500: {type(e).__name__}: {e}"
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):  # scraper gone
            return
        if metrics_enabled():
            # path label is the bounded route set, never the raw path
            route = path if path in ("/", "/metrics", "/varz", "/healthz",
                                     "/readyz", "/statusz", "/tracez",
                                     "/requestz", "/compilez") \
                else "other"
            reg = get_registry()
            reg.inc("alink_admin_requests_total", 1,
                    {"path": route, "code": code})
            reg.observe("alink_admin_scrape_seconds",
                        time.perf_counter() - t0, {"path": route})


class AdminServer:
    """The live-operations HTTP endpoint (see module docstring).

    Construct directly for tests/tools (``port<=0`` binds an ephemeral
    OS-assigned port; the resolved one is :attr:`port`), or let
    components share the flag-armed process instance via
    :func:`acquire_admin`/:func:`release_admin`.
    """

    ENDPOINTS = ("/metrics", "/varz", "/healthz", "/readyz", "/statusz",
                 "/tracez", "/requestz", "/compilez")

    def __init__(self, port: Optional[int] = None,
                 host: Optional[str] = None, name: str = "alink"):
        self.requested_port = admin_port() if port is None else int(port)
        self.host = admin_host() if host is None else str(host)
        self.name = name
        self.port: Optional[int] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._sources: Dict[str, Callable[[], dict]] = {}
        self._status: Dict[str, Callable[[], Any]] = {}
        self._started_unix = time.time()

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "AdminServer":
        bind = self.requested_port if self.requested_port > 0 else 0
        httpd = ThreadingHTTPServer((self.host, bind), _Handler)
        httpd.daemon_threads = True
        httpd.admin = self  # type: ignore[attr-defined]
        self._httpd = httpd
        self.port = httpd.server_address[1]
        self._started_unix = time.time()
        self._thread = threading.Thread(
            target=httpd.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True, name=f"alink-adminz-{self.name}")
        self._thread.start()
        if metrics_enabled():
            get_registry().set_gauge("alink_admin_port", self.port)
        return self

    def close(self) -> None:
        with self._lock:
            httpd, self._httpd = self._httpd, None
            thread, self._thread = self._thread, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    @property
    def url(self) -> str:
        host = "127.0.0.1" if self.host in ("", "0.0.0.0") else self.host
        return f"http://{host}:{self.port}"

    def __enter__(self) -> "AdminServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- source / status registration ------------------------------------
    def add_source(self, name: str, fn: Callable[[], dict]) -> None:
        """Register a readiness source: ``fn()`` returns a dict with at
        least ``{"ready": bool}`` (``"healthy"`` defaults to ready).
        Re-registering a name replaces it (restart-friendly)."""
        with self._lock:
            self._sources[str(name)] = fn

    def remove_source(self, name: str) -> None:
        with self._lock:
            self._sources.pop(str(name), None)

    def add_status(self, name: str, fn: Callable[[], Any]) -> None:
        """Register a ``/statusz`` section: ``fn()`` returns any
        JSON-coercible document rendered under ``sections[name]``."""
        with self._lock:
            self._status[str(name)] = fn

    def remove_status(self, name: str) -> None:
        with self._lock:
            self._status.pop(str(name), None)

    # -- verdicts ---------------------------------------------------------
    def _probe_sources(self) -> Dict[str, dict]:
        with self._lock:
            sources = dict(self._sources)
        out: Dict[str, dict] = {}
        for name, fn in sorted(sources.items()):
            try:
                doc = dict(fn())
            except Exception as e:
                doc = {"ready": False, "healthy": False,
                       "error": f"{type(e).__name__}: {e}"}
            doc.setdefault("ready", False)
            doc.setdefault("healthy", bool(doc["ready"]))
            out[name] = doc
        return out

    def health(self) -> Tuple[bool, dict]:
        """Liveness: every source healthy (an open breaker, a dead
        feeder, an aborted stage report unhealthy). No sources = a
        bare process serving its registry: healthy."""
        probes = self._probe_sources()
        ok = all(bool(d.get("healthy")) for d in probes.values())
        return ok, {"healthy": ok,
                    "sources": _json_safe(probes)}

    def readiness(self) -> Tuple[bool, dict]:
        """Readiness: every source ready. SLO burn monitors register as
        sources too, so a critical fast-window burn flips this to 503
        while it is active."""
        probes = self._probe_sources()
        ok = all(bool(d.get("ready")) for d in probes.values())
        return ok, {"ready": ok, "sources": _json_safe(probes)}

    # -- documents --------------------------------------------------------
    def _index(self) -> str:
        lines = [f"alink_tpu admin plane ({self.name}) — endpoints:"]
        lines += [f"  {p}" for p in self.ENDPOINTS]
        return "\n".join(lines) + "\n"

    def _varz(self) -> list:
        """The registry as JSON records — the ``dump()`` JSONL shape
        (meta record first), so dump-file consumers work unmodified."""
        reg = get_registry()
        meta = {"kind": "meta", "format": "alink_tpu_metrics_v1",
                "created_unix": reg._created_unix,
                "dumped_unix": time.time(),
                "dropped_series": reg._dropped_series}
        return [meta] + reg.snapshot()

    def statusz(self) -> dict:
        """Build info + every resolved flag + registered sections."""
        jax_mod = sys.modules.get("jax")
        flags: Dict[str, Any] = {}
        for f in FLAGS:
            import os
            raw = os.environ.get(f.name)
            try:
                val = f.read()
            except (TypeError, ValueError):
                val = raw
            flags[f.name] = {"kind": f.kind, "value": val,
                             "default": f.default,
                             "set": raw is not None,
                             "section": f.section}
        with self._lock:
            sections = dict(self._status)
        docs: Dict[str, Any] = {}
        for name, fn in sorted(sections.items()):
            try:
                docs[name] = fn()
            except Exception as e:
                docs[name] = {"error": f"{type(e).__name__}: {e}"}
        import os
        return {
            "name": self.name,
            "build": {
                "python": sys.version.split()[0],
                "jax": getattr(jax_mod, "__version__", None),
                "argv0": sys.argv[0] if sys.argv else None,
            },
            "pid": os.getpid(),
            "uptime_s": round(time.time() - self._started_unix, 3),
            "url": self.url,
            "flags": flags,
            "sections": docs,
        }

    def _tracez(self, n: Optional[int] = None,
                trace_id: Optional[str] = None) -> dict:
        """A bounded flight-recorder snapshot: the ring's meta plus the
        LAST ``n`` events (default ``ALINK_TPU_ADMIN_TRACEZ``).
        ``?trace_id=`` keeps only events whose args carry that request
        id (still clamped — the filter narrows, never widens)."""
        from .tracing import get_tracer
        tr = get_tracer()
        cap = admin_tracez_events()
        n = cap if n is None else max(1, min(int(n), cap))
        events = tr.events()
        total = len(events)
        if trace_id is not None:
            events = [e for e in events
                      if (e.get("args") or {}).get("trace_id") == trace_id]
        doc = {"meta": tr._meta(), "returned": min(n, len(events)),
               "total_buffered": total, "events": events[-n:]}
        if trace_id is not None:
            doc["trace_id"] = trace_id
        return doc

    def _requestz(self, n: Optional[int] = None,
                  trace_id: Optional[str] = None,
                  tenant: Optional[str] = None) -> dict:
        """Recent request timelines from the Layer-6 flight recorder
        (:mod:`~alink_tpu.common.reqtrace`): completed requests newest
        first, plus everything currently in flight. ``?n=`` is clamped
        to ``ALINK_TPU_ADMIN_REQUESTZ``; ``?trace_id=`` / ``?tenant=``
        filter (an exact trace_id match also searches in-flight)."""
        from . import reqtrace
        cap = admin_requestz_entries()
        n = cap if n is None else max(1, min(int(n), cap))
        recent = reqtrace.recent(n=n, tenant=tenant, trace_id=trace_id)
        inflight = reqtrace.inflight_docs()
        if tenant is not None:
            inflight = [d for d in inflight if d.get("tenant") == tenant]
        if trace_id is not None:
            inflight = [d for d in inflight
                        if d.get("trace_id") == trace_id]
        return {"enabled": reqtrace.reqtrace_enabled(),
                "returned": len(recent), "inflight": inflight,
                "events": reqtrace.recent_events(n),
                "requests": recent}


# -- the refcounted process-wide instance ---------------------------------
# The first flag-armed component up brings the endpoint up; the last one
# down takes it down. Components NEVER own the port — an OnlineDag and
# the PredictServer inside it share one server and one /statusz.

_shared_lock = threading.Lock()
_shared: Optional[AdminServer] = None
_shared_refs = 0
_bind_warned = False


def acquire_admin(name: str = "alink") -> Optional[AdminServer]:
    """The shared admin endpoint, started on first acquisition when
    ``ALINK_TPU_ADMIN_PORT`` is armed; ``None`` when the plane is off
    (the default) or the bind failed (warned once; the component runs
    on, unobserved — an ops plane must never take the workload down)."""
    global _shared, _shared_refs, _bind_warned
    if not admin_enabled():
        return None
    with _shared_lock:
        if _shared is None:
            try:
                _shared = AdminServer(name=name).start()
            except OSError as e:
                if not _bind_warned:
                    _bind_warned = True
                    warnings.warn(
                        f"adminz: could not bind the admin endpoint "
                        f"({admin_host()}:{admin_port()}): {e} — the "
                        f"live operations plane is OFF for this process",
                        RuntimeWarning, stacklevel=3)
                if metrics_enabled():
                    get_registry().inc("alink_admin_bind_errors_total", 1)
                return None
        _shared_refs += 1
        return _shared


def release_admin() -> None:
    """Drop one acquisition; the endpoint closes when the last holder
    releases."""
    global _shared, _shared_refs
    with _shared_lock:
        if _shared is None:
            return
        _shared_refs -= 1
        if _shared_refs <= 0:
            srv, _shared, _shared_refs = _shared, None, 0
        else:
            return
    srv.close()


def get_admin() -> Optional[AdminServer]:
    """The live shared endpoint, if one is up (tests/smokes use this to
    discover the ephemeral port)."""
    return _shared
