"""Tree-family tests: GBDT / RandomForest / DecisionTree, cls + reg."""

import json

import numpy as np
import pytest

from alink_tpu.operator.base import TableSourceBatchOp
from alink_tpu.operator.batch.source import MemSourceBatchOp
from alink_tpu.operator.batch.classification.tree_ops import (
    GbdtTrainBatchOp, GbdtPredictBatchOp, GbdtRegTrainBatchOp,
    GbdtRegPredictBatchOp, RandomForestTrainBatchOp, RandomForestPredictBatchOp,
    DecisionTreeTrainBatchOp, DecisionTreePredictBatchOp,
    RandomForestRegTrainBatchOp, RandomForestRegPredictBatchOp,
    TreeModelDataConverter)
from alink_tpu.operator.batch.evaluation import EvalBinaryClassBatchOp


def _nonlinear_cls(n=800, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.rand(n, 4)
    # axis-aligned nonlinear rule — tree-friendly, linear-hostile
    y = np.where((X[:, 0] > 0.5) ^ (X[:, 1] > 0.5), "pos", "neg")
    cols = "a DOUBLE, b DOUBLE, c DOUBLE, d DOUBLE, label STRING"
    return MemSourceBatchOp([tuple(r) + (t,) for r, t in zip(X, y)], cols), X, y


def test_gbdt_classifier():
    src, X, y = _nonlinear_cls()
    train = GbdtTrainBatchOp(feature_cols=["a", "b", "c", "d"],
                             label_col="label", num_trees=30, max_depth=4,
                             learning_rate=0.3).link_from(src)
    out = (GbdtPredictBatchOp(prediction_col="pred", prediction_detail_col="dt")
           .link_from(train, src)).collect_mtable()
    acc = np.mean([p == l for p, l in zip(out.col("pred"), out.col("label"))])
    assert acc > 0.95
    m = (EvalBinaryClassBatchOp(label_col="label", prediction_detail_col="dt")
         .link_from(TableSourceBatchOp(out))).collect_metrics()
    assert m.get("AUC") > 0.98
    losses = np.asarray(train.get_side_output(0).get_output_table().col("loss"))
    assert losses[-1] < losses[0] * 0.5


def test_gbdt_regression():
    rng = np.random.RandomState(1)
    n = 600
    X = rng.rand(n, 3)
    y = np.sin(4 * X[:, 0]) + (X[:, 1] > 0.6) * 2.0 + 0.05 * rng.randn(n)
    src = MemSourceBatchOp([tuple(r) + (t,) for r, t in zip(X, y)],
                           "a DOUBLE, b DOUBLE, c DOUBLE, y DOUBLE")
    train = GbdtRegTrainBatchOp(feature_cols=["a", "b", "c"], label_col="y",
                                num_trees=60, max_depth=4,
                                learning_rate=0.2).link_from(src)
    out = (GbdtRegPredictBatchOp(prediction_col="p").link_from(train, src)
           ).collect_mtable()
    rmse = np.sqrt(np.mean((np.asarray(out.col("p")) - y) ** 2))
    assert rmse < 0.25


def test_random_forest_multiclass():
    rng = np.random.RandomState(2)
    n = 600
    X = rng.rand(n, 3)
    y = np.select([X[:, 0] > 0.66, X[:, 0] > 0.33], ["hi", "mid"], "lo")
    src = MemSourceBatchOp([tuple(r) + (t,) for r, t in zip(X, y)],
                           "a DOUBLE, b DOUBLE, c DOUBLE, label STRING")
    train = RandomForestTrainBatchOp(feature_cols=["a", "b", "c"],
                                     label_col="label", num_trees=20,
                                     max_depth=5, seed=5).link_from(src)
    out = (RandomForestPredictBatchOp(prediction_col="pred",
                                      prediction_detail_col="d")
           .link_from(train, src)).collect_mtable()
    acc = np.mean([p == l for p, l in zip(out.col("pred"), out.col("label"))])
    assert acc > 0.93
    probs = json.loads(out.col("d")[0])
    assert set(probs) == {"hi", "mid", "lo"}


def test_decision_tree_and_converter_roundtrip():
    rng = np.random.RandomState(3)
    X = rng.rand(400, 4)
    y = np.where((X[:, 0] > 0.5) & (X[:, 1] > 0.3), "pos", "neg")
    src = MemSourceBatchOp(
        [tuple(r) + (t,) for r, t in zip(X, y)],
        "a DOUBLE, b DOUBLE, c DOUBLE, d DOUBLE, label STRING")
    train = DecisionTreeTrainBatchOp(feature_cols=["a", "b", "c", "d"],
                                     label_col="label", max_depth=4).link_from(src)
    model = TreeModelDataConverter().load_model(train.get_output_table())
    assert model.features.shape == (1, 15)
    out = (DecisionTreePredictBatchOp(prediction_col="pred")
           .link_from(train, src)).collect_mtable()
    acc = np.mean([p == l for p, l in zip(out.col("pred"), out.col("label"))])
    assert acc > 0.95


def test_random_forest_regression():
    rng = np.random.RandomState(4)
    n = 500
    X = rng.rand(n, 2)
    y = X[:, 0] * 3 + (X[:, 1] > 0.5)
    src = MemSourceBatchOp([tuple(r) + (t,) for r, t in zip(X, y)],
                           "a DOUBLE, b DOUBLE, y DOUBLE")
    train = RandomForestRegTrainBatchOp(feature_cols=["a", "b"], label_col="y",
                                        num_trees=30, max_depth=7,
                                        feature_subsampling_ratio=1.0,
                                        subsampling_ratio=0.9).link_from(src)
    out = (RandomForestRegPredictBatchOp(prediction_col="p")
           .link_from(train, src)).collect_mtable()
    rmse = np.sqrt(np.mean((np.asarray(out.col("p")) - y) ** 2))
    assert rmse < 0.35


def test_gbdt_integer_labels():
    src, X, y = _nonlinear_cls(n=300, seed=5)
    rows = [(float(a), float(b), 1 if t == "pos" else 0)
            for (a, b, _, _), t in zip(X, y)]
    src2 = MemSourceBatchOp(rows, "a DOUBLE, b DOUBLE, label LONG")
    train = GbdtTrainBatchOp(feature_cols=["a", "b"], label_col="label",
                             num_trees=20, max_depth=4).link_from(src2)
    out = (GbdtPredictBatchOp(prediction_col="pred").link_from(train, src2)
           ).collect_mtable()
    assert set(out.col("pred")) <= {0, 1}
    acc = np.mean([p == l for p, l in zip(out.col("pred"), out.col("label"))])
    assert acc > 0.9


class TestLevelHist:
    def test_onehot_matches_scatter(self):
        """The TPU one-hot einsum histogram must agree with the scatter-add
        path (exercised here with f32 one-hots since CPU lacks bf16 dots)."""
        import jax.numpy as jnp
        from alink_tpu.operator.common.tree.hist import level_hist
        rng = np.random.RandomState(11)
        n, F, B, m, n_nodes = 200, 5, 8, 3, 4
        binned = jnp.asarray(rng.randint(0, B, (n, F)).astype(np.int32))
        stats = jnp.asarray(rng.randn(n, m).astype(np.float32))
        node_id = jnp.asarray(rng.randint(0, n_nodes, n).astype(np.int32))
        a = level_hist(binned, stats, node_id, n_nodes, B, use_onehot=False)
        b = level_hist(binned, stats, node_id, n_nodes, B, use_onehot=True,
                       onehot_dtype=jnp.float32)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-5)
