"""alink_tpu — a TPU-native distributed ML platform.

A ground-up JAX/XLA re-design of the capabilities of ZhangYuef/Alink
(Alibaba PAI's Flink-based ML platform): operator DAGs, sklearn-style
pipelines, a BSP iterative-compute engine with XLA collectives, ~full
classical-ML algorithm coverage, online learning, and evaluation —
with Flink task slots replaced by a `jax.sharding.Mesh` of TPU chips.
"""

__version__ = "0.1.0"

from .common import (Params, ParamInfo, WithParams, AlinkTypes, TableSchema,
                     DenseVector, SparseVector, VectorUtil, SparseBatch, DenseMatrix,
                     MTable, MLEnvironment, MLEnvironmentFactory, use_local_env,
                     use_remote_env,
                     StepTimer, named_stage, trace,
                     MetricsRegistry, get_registry, set_registry,
                     metrics_enabled,
                     Tracer, get_tracer, set_tracer, tracing_enabled,
                     HealthAlert, HealthAlertError, HealthMonitor,
                     HealthRule, NonFiniteRule, DivergenceRule, PlateauRule,
                     ThresholdRule, UpdateRatioRule, DriftRule,
                     default_rules, health_enabled)
from .engine import (IterativeComQueue, ComContext, ComputeFunction, AllReduce,
                     AllGather, BroadcastFromWorker0)

# ---------------------------------------------------------------------------
# flat export surface (the PyAlink idiom: every operator / pipeline stage is
# importable from the top-level package — README.md:49-58's
# ``from pyalink.alink import *`` user contract). Resolved lazily (PEP 562)
# so ``import alink_tpu`` stays cheap; the full submodule walk happens on
# the first miss only.
# ---------------------------------------------------------------------------

_EXPORT_ROOTS = ("alink_tpu.operator.batch", "alink_tpu.operator.stream",
                 "alink_tpu.pipeline", "alink_tpu.io")
_exports = None


def _collect_exports():
    import importlib
    import pkgutil
    mapping = {}
    for root in _EXPORT_ROOTS:
        pkg = importlib.import_module(root)
        mods = [root] + [m.name for m in
                         pkgutil.walk_packages(pkg.__path__, root + ".")]
        for name in mods:
            try:
                mod = importlib.import_module(name)
            except Exception:  # optional deps (drivers) may be absent
                continue
            for nm, obj in vars(mod).items():
                if (nm[:1].isupper() and isinstance(obj, type) and
                        getattr(obj, "__module__", "").startswith("alink_tpu")):
                    mapping.setdefault(nm, obj)
    return mapping


def __getattr__(name):
    global _exports
    if name == "__all__":
        # star-import support: `from alink_tpu import *` consults __all__
        # (PEP 562 __getattr__ is reached for it when undefined here)
        if _exports is None:
            _exports = _collect_exports()
        return sorted(set(_exports) |
                      {n for n in globals() if not n.startswith("_")})
    if name.startswith("_"):
        raise AttributeError(name)
    if _exports is None:
        _exports = _collect_exports()
    try:
        obj = _exports[name]
    except KeyError:
        raise AttributeError(f"module 'alink_tpu' has no attribute {name!r}")
    globals()[name] = obj  # cache for subsequent lookups
    return obj


def __dir__():
    global _exports
    if _exports is None:
        _exports = _collect_exports()
    return sorted(set(list(globals()) + list(_exports)))
