"""Hive warehouse-layout reader/writer (the offline half of the connector).

The reference connector (connectors/connector-hive: HiveDB.java,
HiveBatchSource.java) reads a Hive table's *warehouse files* directly —
partitioned ``k=v`` directory trees of ^A-delimited text — with partition
pruning from a ``partitions`` spec (HiveSourceParams.java: "/" separates
partition levels, "," separates alternative specs, e.g.
``ds=20190729/dt=12,ds=20190730``) and static-partition writes
(HiveDB.java:135-178 getStaticPartitionSpec / partition columns appended as
STRING). This module is that file layer, server-free: it understands the
standard layout ``<root>/<db>.db/<table>/<k>=<v>/.../part-*`` with Hive's
text SerDe defaults (field delimiter ``\\x01``, NULL as ``\\N``), so tables
written by a real Hive/Spark install read directly and vice versa. The
live-metastore path stays in io/hive.py (gated on pyhive).

Schema: Hive keeps it in the metastore; here it rides a ``.alink.schema``
sidecar written by ``write_table`` (one line, ``col TYPE, col TYPE``) or is
passed explicitly by the caller. Partition columns are STRING, appended
after the data columns, per Hive semantics.
"""

from __future__ import annotations

import glob
import os
import shutil
from typing import Dict, List, Optional, Sequence, Tuple

from ..common.mtable import MTable
from ..common.types import AlinkTypes, TableSchema

FIELD_DELIM = "\x01"   # Hive LazySimpleSerDe default
NULL_TOKEN = "\\N"
SCHEMA_SIDECAR = ".alink.schema"

# LazySimpleSerDe-style backslash escaping (Hive's ESCAPED BY '\\'):
# without it a ^A, newline, or literal "\N" inside a STRING cell silently
# shifts/splits/nulls fields on read-back.
_ESCAPES = [("\\", "\\\\"), (FIELD_DELIM, "\\" + FIELD_DELIM),
            ("\n", "\\n"), ("\r", "\\r")]


def _escape_cell(s: str) -> str:
    for raw, esc in _ESCAPES:
        s = s.replace(raw, esc)
    return s


def _split_line(line: str) -> List[str]:
    """Split on unescaped FIELD_DELIM. Cells still carry the escape
    placeholders — resolve with ``_finish_cell`` — so NULL detection can
    happen before unescaping (a literal backslash+N cell arrives here as
    placeholder+N and is distinguishable from a genuine ``\\N`` NULL)."""
    line = line.replace("\\\\", "\x00")
    line = line.replace("\\" + FIELD_DELIM, "\x02")
    return line.split(FIELD_DELIM)


def _finish_cell(c: str) -> Optional[str]:
    if c == NULL_TOKEN:
        return None
    return (c.replace("\x02", FIELD_DELIM).replace("\\n", "\n")
            .replace("\\r", "\r").replace("\x00", "\\"))


def parse_partition_spec(spec: str) -> Dict[str, str]:
    """``"ds=20190729/dt=12"`` -> {"ds": "20190729", "dt": "12"}."""
    out: Dict[str, str] = {}
    for level in spec.strip().strip("/").split("/"):
        if not level:
            continue
        if "=" not in level:
            raise ValueError(f"partition level {level!r} is not k=v "
                             f"(spec {spec!r})")
        k, v = level.split("=", 1)
        out[k.strip()] = v.strip()
    return out


def parse_partitions_param(partitions: Optional[str]) -> List[Dict[str, str]]:
    """The source ``partitions`` param: comma-separated alternative specs
    (reference HiveSourceParams.PARTITIONS). Empty/None -> no pruning."""
    if not partitions or not partitions.strip():
        return []
    return [parse_partition_spec(alt) for alt in partitions.split(",")]


def _spec_matches(spec: Dict[str, str],
                  alternatives: List[Dict[str, str]]) -> bool:
    if not alternatives:
        return True
    return any(all(spec.get(k) == v for k, v in alt.items())
               for alt in alternatives)


class HiveWarehouse:
    """A Hive warehouse directory: ``<root>/<db>.db/<table>/...``
    (``default`` database tables live directly under ``<root>``)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)

    def table_dir(self, table: str, db: str = "default") -> str:
        base = self.root if db == "default" else os.path.join(
            self.root, f"{db}.db")
        return os.path.join(base, table)

    def list_tables(self, db: str = "default") -> List[str]:
        base = self.root if db == "default" else os.path.join(
            self.root, f"{db}.db")
        if not os.path.isdir(base):
            return []
        return sorted(d for d in os.listdir(base)
                      if os.path.isdir(os.path.join(base, d))
                      and not d.endswith(".db"))

    # -- read ------------------------------------------------------------
    def _walk_partitions(self, table_dir: str) \
            -> List[Tuple[Dict[str, str], List[str]]]:
        """[(partition_spec, data_files)] — spec {} for unpartitioned."""
        out = []

        def rec(d: str, spec: Dict[str, str]):
            files, subparts = [], []
            for name in sorted(os.listdir(d)):
                p = os.path.join(d, name)
                if os.path.isdir(p) and "=" in name:
                    subparts.append((p, name))
                elif os.path.isfile(p) and not name.startswith((".", "_")):
                    files.append(p)
            if subparts:
                for p, name in subparts:
                    k, v = name.split("=", 1)
                    rec(p, {**spec, k: v})
            if files or not subparts:
                out.append((spec, files))

        rec(table_dir, {})
        return out

    def read_schema(self, table: str, db: str = "default") \
            -> Optional[TableSchema]:
        sidecar = os.path.join(self.table_dir(table, db), SCHEMA_SIDECAR)
        if os.path.isfile(sidecar):
            with open(sidecar, "r", encoding="utf-8") as f:
                return TableSchema.parse(f.read().strip())
        return None

    def read_table(self, table: str, db: str = "default",
                   schema: Optional[TableSchema] = None,
                   partitions: Optional[str] = None) -> MTable:
        """Partition-pruned read; partition columns appended as STRING."""
        tdir = self.table_dir(table, db)
        if not os.path.isdir(tdir):
            raise FileNotFoundError(f"hive table dir not found: {tdir}")
        schema = schema or self.read_schema(table, db)
        if schema is None:
            raise ValueError(
                f"no schema for hive table {db}.{table}: pass schema_str= "
                f"(none found at {os.path.join(tdir, SCHEMA_SIDECAR)})")
        alts = parse_partitions_param(partitions)
        parts = [(spec, files) for spec, files in self._walk_partitions(tdir)
                 if _spec_matches(spec, alts)]
        if alts and not any(files for _, files in parts):
            raise ValueError(f"partitions {partitions!r} matched nothing "
                             f"under {tdir}")
        # partition columns, in first-seen directory order
        pcols: List[str] = []
        for spec, _ in parts:
            for k in spec:
                if k not in pcols:
                    pcols.append(k)
        from .csv import _parse_cell
        rows = []
        for spec, files in parts:
            pvals = tuple(spec.get(k) for k in pcols)
            for path in files:
                with open(path, "r", encoding="utf-8") as f:
                    for line in f:
                        line = line.rstrip("\n")
                        if not line:
                            continue
                        cells = _split_line(line)
                        vals = []
                        for j, t in enumerate(schema.types):
                            raw = cells[j] if j < len(cells) else None
                            s = _finish_cell(raw) if raw is not None else None
                            vals.append(_parse_cell(s, t)
                                        if s is not None else None)
                        rows.append(tuple(vals) + pvals)
        out_schema = TableSchema(
            list(schema.names) + pcols,
            list(schema.types) + [AlinkTypes.STRING] * len(pcols))
        return MTable(rows, out_schema)

    # -- write -----------------------------------------------------------
    def write_table(self, table: str, mt: MTable, db: str = "default",
                    partition: Optional[str] = None,
                    overwrite: bool = False) -> None:
        """Hive-text write; ``partition`` is a static spec ``k=v/k2=v2``
        (reference HiveSinkParams.PARTITION) selecting the target dir."""
        tdir = self.table_dir(table, db)
        spec = parse_partition_spec(partition) if partition else {}
        dest = tdir
        for k, v in spec.items():
            dest = os.path.join(dest, f"{k}={v}")
        if overwrite and os.path.isdir(dest):
            shutil.rmtree(dest)
        os.makedirs(dest, exist_ok=True)
        sidecar = os.path.join(tdir, SCHEMA_SIDECAR)
        schema_line = ", ".join(f"{n} {t}" for n, t in
                                zip(mt.schema.names, mt.schema.types))
        if os.path.isfile(sidecar):
            with open(sidecar, "r", encoding="utf-8") as f:
                existing = f.read().strip()
            if existing.lower() != schema_line.lower():
                raise ValueError(
                    f"schema mismatch writing {db}.{table}: table has "
                    f"[{existing}], input is [{schema_line}]")
        else:
            with open(sidecar, "w", encoding="utf-8") as f:
                f.write(schema_line + "\n")
        seq = len(glob.glob(os.path.join(dest, "part-*")))
        out_path = os.path.join(dest, f"part-{seq:05d}")
        from ..common.vector import VectorUtil
        with open(out_path, "w", encoding="utf-8") as f:
            for row in mt.rows():
                cells = []
                for v, t in zip(row, mt.schema.types):
                    if v is None:
                        cells.append(NULL_TOKEN)
                    elif AlinkTypes.is_vector(t):
                        cells.append(_escape_cell(
                            VectorUtil.to_string(VectorUtil.parse(v))))
                    else:
                        cells.append(_escape_cell(str(v)))
                f.write(FIELD_DELIM.join(cells) + "\n")
