"""Structured tracing — hierarchical spans, instant events, flight recorder.

The reference gets a *timeline* for free: the Flink web UI draws every
job's operator tasks against wall time, so "which stage of which superstep
was slow" is one click. The TPU build's aggregate metrics
(``common/metrics.py``) answer "how much, in total" but cannot answer
"when, and inside what" — that needs a trace: a tree of timed spans plus
point events, exactly what the JAX ecosystem's profiler/TensorBoard trace
viewer provides for *device* time. This module is the **host-side**
counterpart, instrumenting the runtime's own control flow:

  * ``Tracer.span(name)`` — a context manager that records one *complete*
    span (start + duration). Nesting is automatic: the current span is
    carried in a ``contextvars.ContextVar``, so a span opened inside
    another becomes its child — across ``with`` blocks, call stacks and
    (because each thread starts a fresh context) cleanly per thread.
  * ``Tracer.instant(name)`` — a zero-duration marker (checkpoint saved,
    program-cache hit, fault injected), parented to the current span.
  * **flight recorder** — events land in a bounded ring buffer
    (``collections.deque(maxlen=...)``); when full, the *oldest* events
    fall out and a drop counter advances. Always-on tracing is therefore
    memory-safe in production: the buffer holds the most recent history,
    like an aircraft flight recorder.

Two exporters:

  * ``export_chrome(path)`` — Chrome Trace Event Format JSON, loadable in
    Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``;
  * ``export_jsonl(path)`` — one JSON object per line (meta record first),
    the run-log shape ``tools/trace.py`` and ``tools/run_report.py
    --trace`` consume.

Switches (``common.metrics.env_flag`` parsing: unset -> default,
``0/false/off/no`` -> off):

  * ``ALINK_TPU_TRACE``        — default OFF. Master switch for every
    instrumented producer (``trace_span``/``trace_instant`` below are
    no-ops without it). Tracing never changes compiled programs — all
    events are host-side (asserted by a lowered-HLO test).
  * ``ALINK_TPU_TRACE_BUFFER`` — flight-recorder capacity in events
    (default 65536; ~200 bytes/event, so the default bounds memory at a
    few tens of MB).

Instrumented producers (engine exec/chunk phases, batch ``link_from``,
stream micro-batches, FTRL, checkpoint save/restore, fault injection) all
go through the module-level :func:`trace_span` / :func:`trace_instant`
helpers, which gate on the env switch and the process-wide tracer
(:func:`get_tracer` / :func:`set_tracer`, mirroring the metrics registry).
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

from .metrics import env_flag

__all__ = [
    "Tracer", "Span", "get_tracer", "set_tracer", "tracing_enabled",
    "trace_span", "trace_instant", "trace_complete", "events_to_chrome",
    "TRACE_ENV", "TRACE_BUFFER_ENV", "DEFAULT_BUFFER_EVENTS",
]

TRACE_ENV = "ALINK_TPU_TRACE"
TRACE_BUFFER_ENV = "ALINK_TPU_TRACE_BUFFER"
DEFAULT_BUFFER_EVENTS = 65536

TRACE_FORMAT = "alink_tpu_trace_v1"


def tracing_enabled() -> bool:
    """``ALINK_TPU_TRACE`` switch (default off). Read live, so tests and
    long-lived processes can toggle it per run."""
    return env_flag(TRACE_ENV, default=False)


def _buffer_capacity() -> int:
    # registry-declared (common/flags.py): tolerant int parse, clamped
    # to >= 1, default DEFAULT_BUFFER_EVENTS — exactly the historical
    # semantics, now shared with the generated docs table
    from .flags import flag_value
    return flag_value(TRACE_BUFFER_ENV, DEFAULT_BUFFER_EVENTS)


# The current span rides in a ContextVar, NOT a thread-local: nesting must
# survive ``with``-block composition inside one task while new threads
# (stream prefetch, bench workers) start with a fresh context — each
# thread becomes its own root lane in the exported timeline.
_current_span: "contextvars.ContextVar[Optional[Span]]" = \
    contextvars.ContextVar("alink_tpu_trace_span", default=None)


class Span:
    """One open span. Use as a context manager (``Tracer.span`` returns
    it unentered); mutate ``args`` mid-flight via :meth:`set` — e.g. a
    cache status only known at the end of the region."""

    __slots__ = ("name", "cat", "args", "id", "parent", "tid",
                 "_tracer", "_start_ns", "_token")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args: Dict[str, Any] = dict(args) if args else {}
        self.id = 0
        self.parent: Optional[int] = None
        self.tid = 0
        self._start_ns = 0
        self._token = None

    def set(self, **kw) -> "Span":
        """Attach/overwrite args on the open span (chainable)."""
        self.args.update(kw)
        return self

    def __enter__(self) -> "Span":
        cur = _current_span.get()
        self.parent = cur.id if cur is not None else None
        self.id = self._tracer._next_id()
        self.tid = threading.get_ident()
        self._token = _current_span.set(self)
        self._start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        end_ns = time.perf_counter_ns()
        if self._token is not None:
            _current_span.reset(self._token)
            self._token = None
        self._tracer._record(
            ph="X", name=self.name, cat=self.cat,
            ts_ns=self._start_ns, dur_ns=end_ns - self._start_ns,
            tid=self.tid, id=self.id, parent=self.parent,
            args=self.args or None)
        return False


class _NullSpan:
    """Shared no-op stand-in returned by :func:`trace_span` when tracing
    is off — zero allocation on the fast path. ``set`` discards."""

    __slots__ = ()

    def set(self, **kw) -> "_NullSpan":
        return self

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class Tracer:
    """Thread-safe span tracer with a bounded in-memory ring buffer.

    >>> tr = Tracer()
    >>> with tr.span("exec"):
    ...     with tr.span("prepare"):
    ...         pass
    ...     tr.instant("cache", args={"result": "hit"})
    >>> tr.export_chrome("/tmp/trace.json")   # open in Perfetto

    Events are plain dicts ``{ph, name, cat, ts, dur, tid, id, parent,
    args}`` with ``ts``/``dur`` in microseconds relative to the tracer's
    start. ``ph`` follows the Chrome Trace Event phases this module
    emits: ``X`` (complete span) and ``i`` (instant). The buffer holds
    the newest ``capacity`` events; older ones are dropped and counted
    (``dropped``), never grown past the bound.
    """

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = int(capacity) if capacity is not None \
            else _buffer_capacity()
        if self.capacity < 1:
            raise ValueError(f"tracer capacity must be >= 1, "
                             f"got {self.capacity}")
        self._events: "deque[Dict[str, Any]]" = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._dropped = 0
        self._id = 0
        self._origin_ns = time.perf_counter_ns()
        self._origin_unix = time.time()
        self._thread_names: Dict[int, str] = {}

    # -- recording --------------------------------------------------------
    def _next_id(self) -> int:
        with self._lock:
            self._id += 1
            return self._id

    def _record(self, *, ph: str, name: str, cat: str, ts_ns: int,
                dur_ns: Optional[int], tid: int, id: Optional[int],
                parent: Optional[int], args: Optional[Dict[str, Any]]):
        ev: Dict[str, Any] = {
            "ph": ph, "name": name, "cat": cat,
            "ts": (ts_ns - self._origin_ns) / 1e3,  # microseconds
            "tid": tid,
        }
        if dur_ns is not None:
            ev["dur"] = dur_ns / 1e3
        if id is not None:
            ev["id"] = id
        if parent is not None:
            ev["parent"] = parent
        if args:
            ev["args"] = args
        with self._lock:
            if tid not in self._thread_names:
                t = threading.current_thread()
                self._thread_names[tid] = t.name
            if len(self._events) == self.capacity:
                self._dropped += 1      # deque(maxlen) evicts the oldest
            self._events.append(ev)

    def span(self, name: str, cat: str = "host",
             args: Optional[Dict[str, Any]] = None) -> Span:
        """A new (unentered) span; enter it with ``with``."""
        return Span(self, name, cat, args)

    def instant(self, name: str, cat: str = "host",
                args: Optional[Dict[str, Any]] = None) -> None:
        """Record a point event, parented to the current span."""
        cur = _current_span.get()
        self._record(ph="i", name=name, cat=cat,
                     ts_ns=time.perf_counter_ns(), dur_ns=None,
                     tid=threading.get_ident(), id=self._next_id(),
                     parent=cur.id if cur is not None else None, args=args)

    def complete(self, name: str, dur_s: float, cat: str = "host",
                 args: Optional[Dict[str, Any]] = None) -> None:
        """Record a span retroactively: it ends *now* and lasted
        ``dur_s``. For regions timed with an existing ``perf_counter``
        pair where entering a context manager is awkward (e.g. generator
        bodies that must not hold a context across a ``yield`` — the
        caller's context would inherit the open span)."""
        cur = _current_span.get()
        end_ns = time.perf_counter_ns()
        dur_ns = max(0, int(dur_s * 1e9))
        self._record(ph="X", name=name, cat=cat, ts_ns=end_ns - dur_ns,
                     dur_ns=dur_ns, tid=threading.get_ident(),
                     id=self._next_id(),
                     parent=cur.id if cur is not None else None, args=args)

    # -- reading / management ---------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        """Snapshot of buffered events in timestamp order."""
        with self._lock:
            evs = list(self._events)
        return sorted(evs, key=lambda e: e["ts"])

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0

    # -- exporters --------------------------------------------------------
    def _meta(self) -> Dict[str, Any]:
        with self._lock:
            return {"kind": "meta", "format": TRACE_FORMAT,
                    "origin_unix": self._origin_unix,
                    "exported_unix": time.time(),
                    "capacity": self.capacity, "dropped": self._dropped,
                    "threads": {str(k): v
                                for k, v in self._thread_names.items()}}

    def to_chrome(self) -> Dict[str, Any]:
        """Chrome Trace Event Format object (``{"traceEvents": [...]}``).

        Span ids/parents ride in each event's ``args`` (``span_id`` /
        ``parent_id``) so the tree survives the format round-trip —
        Perfetto itself nests by interval containment per tid.
        """
        return events_to_chrome(self._meta(), self.events())

    def export_chrome(self, path: str) -> str:
        """Write the Chrome-trace JSON; open in Perfetto or
        ``chrome://tracing``. Returns ``path``."""
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome(), f)
        os.replace(tmp, path)
        return path

    def export_jsonl(self, path: str) -> str:
        """Write the JSONL run log (meta line first, then one event per
        line, timestamp-ordered). Returns ``path``."""
        lines = [json.dumps(self._meta())]
        lines += [json.dumps(ev) for ev in self.events()]
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            f.write("\n".join(lines))
            f.write("\n")
        os.replace(tmp, path)
        return path


def events_to_chrome(meta: Dict[str, Any],
                     events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome Trace Event Format document from normalized tracer events.

    The ONE emitter of the Chrome mapping — ``Tracer.to_chrome`` and the
    ``tools/trace.py --chrome`` conversion both delegate here, so the two
    can never drift. ``meta`` is a ``Tracer._meta()``-shaped dict (only
    ``threads`` and the passthrough keys are read)."""
    out: List[Dict[str, Any]] = [
        {"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
         "args": {"name": "alink_tpu"}}]
    for tid, tname in sorted((meta.get("threads") or {}).items()):
        out.append({"ph": "M", "name": "thread_name", "pid": 1,
                    "tid": int(tid), "args": {"name": tname}})
    for ev in events:
        ce: Dict[str, Any] = {"ph": ev["ph"], "name": ev["name"],
                              "cat": ev.get("cat", "?"), "pid": 1,
                              "tid": ev["tid"], "ts": ev["ts"]}
        if ev["ph"] == "X":
            ce["dur"] = ev.get("dur", 0.0)
        else:
            ce["s"] = "t"               # instant scoped to its thread
        args = dict(ev.get("args") or {})
        if "id" in ev:
            args["span_id"] = ev["id"]
        if "parent" in ev:
            args["parent_id"] = ev["parent"]
        if args:
            ce["args"] = args
        out.append(ce)
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {k: v for k, v in meta.items()
                          if k not in ("kind", "threads")}}


# -- the process-wide tracer ------------------------------------------------

# created lazily so ALINK_TPU_TRACE_BUFFER set after import (but before
# first use) still sizes it; capacity latches at first get_tracer()
_default_tracer: Optional[Tracer] = None
_default_lock = threading.Lock()


def get_tracer() -> Tracer:
    """The flight recorder every runtime producer reports into."""
    global _default_tracer
    if _default_tracer is None:
        with _default_lock:
            if _default_tracer is None:
                _default_tracer = Tracer()
    return _default_tracer


def set_tracer(tracer: Tracer) -> Tracer:
    """Swap the process-wide tracer (per-run isolation, tests); returns
    the previous one (created on the spot if none existed yet)."""
    global _default_tracer
    with _default_lock:
        prev = _default_tracer if _default_tracer is not None else Tracer()
        _default_tracer = tracer
    return prev


# -- instrumentation helpers (the call-site API) ----------------------------

def trace_span(name: str, cat: str = "host",
               args: Optional[Dict[str, Any]] = None):
    """A span on the process tracer, or a shared no-op when
    ``ALINK_TPU_TRACE`` is off. The disabled fast path costs one env
    lookup and allocates nothing."""
    if not tracing_enabled():
        return _NULL_SPAN
    return get_tracer().span(name, cat=cat, args=args)


def trace_instant(name: str, cat: str = "host",
                  args: Optional[Dict[str, Any]] = None) -> None:
    """An instant event on the process tracer; no-op when tracing is off."""
    if tracing_enabled():
        get_tracer().instant(name, cat=cat, args=args)


def trace_complete(name: str, dur_s: float, cat: str = "host",
                   args: Optional[Dict[str, Any]] = None) -> None:
    """A retroactive span (ends now, lasted ``dur_s``) on the process
    tracer; no-op when tracing is off. See :meth:`Tracer.complete`."""
    if tracing_enabled():
        get_tracer().complete(name, dur_s, cat=cat, args=args)
