from .converters import (ModelDataConverter, SimpleModelDataConverter,
                         LabeledModelDataConverter)

__all__ = ["ModelDataConverter", "SimpleModelDataConverter", "LabeledModelDataConverter"]
