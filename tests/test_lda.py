"""LDA tests — synthetic two-topic corpus; both EM and online methods must
recover the topic split (reference test style: LdaTrainBatchOpTest asserts
fit+transform end-to-end)."""

import json

import numpy as np
import pytest

from alink_tpu.operator.batch.source import MemSourceBatchOp
from alink_tpu.operator.batch.clustering.lda_ops import (
    LdaModelDataConverter, LdaPredictBatchOp, LdaTrainBatchOp)
from alink_tpu.pipeline.base import Pipeline
from alink_tpu.pipeline.clustering import Lda


SPORT = ["ball game team win score play match goal",
         "team play ball match score win",
         "game win team goal ball score",
         "match play goal win game team ball",
         "score goal match team play win"]
COOK = ["salt oil pan cook recipe dish flavor taste",
        "recipe dish salt cook taste oil",
        "cook pan flavor dish recipe salt",
        "taste oil cook salt dish pan recipe",
        "flavor dish taste cook oil recipe"]


def _src():
    docs = []
    for i in range(4):
        docs += [(s + f" extra{i}",) for s in SPORT]
        docs += [(c + f" extra{i}",) for c in COOK]
    return MemSourceBatchOp(docs, "doc STRING"), len(SPORT) * 4


@pytest.mark.parametrize("method", ["em", "online"])
def test_lda_separates_topics(method):
    src, n_sport = _src()
    train = LdaTrainBatchOp(selected_col="doc", topic_num=2, method=method,
                            num_iter=30, subsampling_rate=0.8,
                            seed=7).link_from(src)
    model = LdaModelDataConverter().load_model(train.get_output_table())
    assert model.gamma.shape[1] == 2
    assert len(model.vocab) > 10
    assert model.log_perplexity > 0

    pred = LdaPredictBatchOp(selected_col="doc", prediction_col="topic",
                             prediction_detail_col="detail").link_from(train, src)
    out = pred.collect_mtable()
    topics = np.asarray(out.col("topic"))
    sport_topics, cook_topics = topics[:n_sport], topics[n_sport:]
    # interleaved blocks of 5; majority label per group must differ
    s_maj = np.bincount(topics[np.arange(len(topics)) % 10 < 5], minlength=2).argmax()
    c_maj = np.bincount(topics[np.arange(len(topics)) % 10 >= 5], minlength=2).argmax()
    assert s_maj != c_maj
    det = json.loads(out.col("detail")[0])
    assert len(det) == 2 and abs(sum(det) - 1.0) < 1e-3


def test_lda_pipeline_roundtrip(tmp_path):
    src, _ = _src()
    lda = Lda(selected_col="doc", topic_num=2, num_iter=15, seed=3,
              prediction_col="topic")
    pm = Pipeline(lda).fit(src)
    out1 = pm.transform(src).collect_mtable()
    path = str(tmp_path / "lda_model")
    pm.save(path)
    from alink_tpu.pipeline.base import PipelineModel
    out2 = PipelineModel.load(path).transform(src).collect_mtable()
    assert np.array_equal(np.asarray(out1.col("topic")),
                          np.asarray(out2.col("topic")))


def test_gibbs_lda_recovers_topics_and_matches_variational():
    """VERDICT r2 #7: the collapsed-Gibbs path (AD-LDA, device-resident
    per-token assignments, categorical sampling, psum'd counts) trains on
    the mesh and reaches perplexity comparable to the variational EM path
    on the same fixture corpus."""
    import numpy as np
    from alink_tpu.operator.common.clustering.lda import (
        em_lda_train, encode_corpus, expand_tokens, gibbs_lda_train)

    # two planted topics over a 20-word vocab
    rng = np.random.RandomState(0)
    V, k, n_docs = 20, 2, 120
    topic_a = np.zeros(V); topic_a[:10] = 1.0 / 10
    topic_b = np.zeros(V); topic_b[10:] = 1.0 / 10
    vocab = [f"w{i}" for i in range(V)]
    texts = []
    for d in range(n_docs):
        dist = topic_a if d % 2 == 0 else topic_b
        words = rng.choice(V, size=30, p=dist)
        texts.append(" ".join(vocab[w] for w in words))
    index = {w: i for i, w in enumerate(vocab)}
    ids, cnts = encode_corpus(texts, index)

    tok, mask = expand_tokens(ids, cnts)
    assert tok.shape[0] == n_docs and mask.sum() == cnts.sum()

    wt_g, tot_g, a_g, b_g, ll_g, perp_g = gibbs_lda_train(
        ids, cnts, k, V, num_iter=60, seed=0)
    assert wt_g.shape == (V, k) and np.isfinite(perp_g)
    # counts conserved: every token occurrence lands in exactly one topic
    np.testing.assert_allclose(wt_g.sum(), cnts.sum())
    # topic recovery: each learned topic concentrates on one planted half
    share = wt_g[:10, :].sum(0) / np.maximum(wt_g.sum(0), 1e-9)
    assert (share.max() > 0.85) and (share.min() < 0.15), share

    _, _, _, _, _, perp_em = em_lda_train(ids, cnts, k, V, num_iter=30,
                                          seed=0)
    # same corpus, same model family: log-perplexities in the same band
    assert abs(perp_g - perp_em) < 0.35, (perp_g, perp_em)


def test_gibbs_lda_batch_op_end_to_end():
    import numpy as np
    from alink_tpu.operator.batch.source.sources import MemSourceBatchOp
    from alink_tpu.operator.batch.clustering.lda_ops import (
        LdaPredictBatchOp, LdaTrainBatchOp)

    rng = np.random.RandomState(1)
    rows = []
    for d in range(60):
        if d % 2 == 0:
            words = rng.choice(["apple", "pear", "grape", "melon"], 12)
        else:
            words = rng.choice(["car", "bus", "train", "plane"], 12)
        rows.append((" ".join(words),))
    src = MemSourceBatchOp(rows, "doc STRING")
    train = LdaTrainBatchOp(selected_col="doc", topic_num=2,
                            method="em_gibbs", num_iter=40).link_from(src)
    pred = LdaPredictBatchOp(selected_col="doc",
                             prediction_col="topic").link_from(train, src)
    out = pred.collect()
    topics = [r[-1] for r in out]
    fruit = {topics[i] for i in range(0, 60, 2)}
    vehicle = {topics[i] for i in range(1, 60, 2)}
    # the two planted doc classes land in distinct dominant topics
    assert len(fruit) == 1 and len(vehicle) == 1 and fruit != vehicle
