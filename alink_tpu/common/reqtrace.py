"""Request-scoped tracing — Layer 6 of the observability stack.

The five layers shipped so far (metrics, flight-recorder spans, run
reports, measured profiling, the admin plane) are all *component*
scoped: they can say the fleet's p99 regressed, not which request sat
behind which eviction, lane rebuild, swap flip or breaker probe. This
module adds the per-request causality substrate:

  * :class:`RequestContext` — one id + a monotonic timeline, minted at
    ``PredictServer``/``FleetServer`` admission and threaded through
    the serving machinery. Call sites ``mark(phase)`` at each hand-off
    (``admit`` → ``dequeue`` → ``coalesce`` → ``dispatch`` →
    ``device`` → ``decode``) and the finished document carries the
    per-phase durations (``queue_s``, ``dispatch_s``, ...).
  * **overlap annotations** — concurrent swap / eviction /
    lane-rebuild / breaker events call :func:`annotate_inflight` and
    every request in flight at that instant gets the event stamped
    onto its timeline (bounded per request), so a tail-latency
    exemplar is *explained*, not just measured. The same events land
    in a bounded process event ring (:func:`recent_events`) — the swap
    history the post-mortem bundle archives.
  * a bounded **finished-request ring** (``ALINK_TPU_REQTRACE_RING``)
    behind :func:`recent` / :func:`find` — what ``/requestz`` serves
    and post-mortem bundles freeze.
  * :func:`batch_scope` / :func:`batch_mark` — a contextvar channel so
    ``CompiledPredictor`` (which knows nothing about requests) can
    stamp its encode/dispatch/device/decode boundaries onto every
    request riding the current batch.

Everything here is host-side bookkeeping (perf_counter reads + list
appends): compiled programs, lowered HLO, and every program-cache key
are byte-identical with request tracing on or off — the same
discipline as the tracing/metrics/admin layers (PRs 3/8/16). The
switch is ``ALINK_TPU_REQTRACE`` (default **on**; the steady cost is a
few timestamps per request, not per row).
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .flags import flag_value
from .tracing import trace_complete, tracing_enabled

__all__ = [
    "RequestContext", "admit", "finish", "annotate_inflight",
    "batch_scope", "batch_mark", "recent", "recent_events", "find",
    "inflight_docs", "p99_exemplar", "reqtrace_enabled",
    "ring_capacity", "reset",
]

#: per-request annotation bound: a swap storm overlapping one slow
#: request must not grow its timeline without limit — beyond this the
#: document records only the overflow count
MAX_ANNOTATIONS = 16
#: mark bound (phases are a fixed small vocabulary; this is a guard
#: against a looping call site, not a tunable)
MAX_MARKS = 32
#: process event ring (swap/evict/lane-rebuild/breaker history)
EVENT_RING = 128

#: mark name -> phase name in the finished document (the queue phase
#: ends at the *dequeue* mark; every other phase is named by the mark
#: that ends it)
_PHASE_OF_MARK = {"dequeue": "queue"}


def reqtrace_enabled() -> bool:
    """Live switch (``ALINK_TPU_REQTRACE``, default on)."""
    return bool(flag_value("ALINK_TPU_REQTRACE", True))


def ring_capacity() -> int:
    return int(flag_value("ALINK_TPU_REQTRACE_RING", 1024))


_id_counter = itertools.count(1)


class RequestContext:
    """One request's monotonic timeline: an id, ``mark()`` timestamps
    (offsets from admission, seconds) and bounded overlap annotations.
    Mutation is append-only from the request's own thread plus
    :func:`annotate_inflight` callers; the per-context lock keeps the
    two from tearing a list."""

    __slots__ = ("trace_id", "tenant", "created_unix", "_t0", "marks",
                 "annotations", "dropped_annotations", "outcome",
                 "_lock")

    def __init__(self, trace_id: str, tenant: Optional[str] = None):
        self.trace_id = trace_id
        self.tenant = tenant
        self.created_unix = time.time()
        self._t0 = time.perf_counter()
        self.marks: List[Tuple[str, float]] = [("admit", 0.0)]
        self.annotations: List[Dict[str, Any]] = []
        self.dropped_annotations = 0
        self.outcome: Optional[str] = None
        self._lock = threading.Lock()

    def mark(self, phase: str) -> None:
        """Timestamp a phase boundary (offset from admission)."""
        t = time.perf_counter() - self._t0
        with self._lock:
            if len(self.marks) < MAX_MARKS:
                self.marks.append((str(phase), t))

    def annotate(self, kind: str, args: Optional[Dict[str, Any]] = None
                 ) -> None:
        """Stamp a concurrent event (swap/evict/breaker/...) onto this
        request's timeline; bounded at :data:`MAX_ANNOTATIONS`."""
        t = time.perf_counter() - self._t0
        with self._lock:
            if len(self.annotations) >= MAX_ANNOTATIONS:
                self.dropped_annotations += 1
                return
            ev: Dict[str, Any] = {"kind": str(kind), "t_s": round(t, 6)}
            if args:
                ev["args"] = dict(args)
            self.annotations.append(ev)

    def elapsed_s(self) -> float:
        return time.perf_counter() - self._t0

    def phase_end(self, phase_or_mark: str) -> Optional[float]:
        """Offset (s) of a named mark — ``phase_end("dispatch")`` is
        the admission→dispatch wait the queue-wait histogram exports."""
        with self._lock:
            for name, t in self.marks:
                if name == phase_or_mark:
                    return t
        return None

    def phases(self) -> Dict[str, float]:
        """Per-phase durations from consecutive marks: ``queue_s`` =
        dequeue − admit, ``dispatch_s`` = dispatch − previous mark, ..."""
        with self._lock:
            marks = list(self.marks)
        out: Dict[str, float] = {}
        for (_, prev_t), (name, t) in zip(marks, marks[1:]):
            out[_PHASE_OF_MARK.get(name, name) + "_s"] = round(
                t - prev_t, 6)
        return out

    def to_doc(self, total_s: Optional[float] = None) -> Dict[str, Any]:
        with self._lock:
            doc: Dict[str, Any] = {
                "trace_id": self.trace_id,
                "created_unix": self.created_unix,
                "marks": [{"phase": n, "t_s": round(t, 6)}
                          for n, t in self.marks],
                "annotations": list(self.annotations),
            }
            if self.tenant is not None:
                doc["tenant"] = self.tenant
            if self.dropped_annotations:
                doc["dropped_annotations"] = self.dropped_annotations
            if self.outcome is not None:
                doc["outcome"] = self.outcome
        doc["phases"] = self.phases()
        if total_s is not None:
            doc["total_s"] = round(total_s, 6)
        return doc


# -- process-wide state ---------------------------------------------------

_lock = threading.Lock()
_inflight: Dict[str, RequestContext] = {}
_ring: deque = deque(maxlen=1024)
_events: deque = deque(maxlen=EVENT_RING)


def _ring_locked() -> deque:
    """The finished-request ring at its flagged capacity (re-created,
    keeping the newest tail, when the flag changed). Caller holds
    ``_lock``."""
    global _ring
    cap = max(1, ring_capacity())
    if _ring.maxlen != cap:
        _ring = deque(_ring, maxlen=cap)
    return _ring


def admit(tenant: Optional[str] = None) -> Optional[RequestContext]:
    """Mint a context at server admission (``None`` when the layer is
    off — every downstream call site tolerates a ``None`` ctx)."""
    if not reqtrace_enabled():
        return None
    ctx = RequestContext(f"r{next(_id_counter):08d}", tenant)
    with _lock:
        _inflight[ctx.trace_id] = ctx
    return ctx


def finish(ctx: Optional[RequestContext],
           outcome: str = "ok") -> Optional[Dict[str, Any]]:
    """Close a request's timeline: move it from the in-flight set to
    the finished ring and (tracing on) emit one ``serve.request``
    complete-event carrying the trace id, so the flight recorder's
    ``/tracez?trace_id=`` view can find it."""
    if ctx is None:
        return None
    total = ctx.elapsed_s()
    ctx.outcome = outcome
    doc = ctx.to_doc(total_s=total)
    with _lock:
        _inflight.pop(ctx.trace_id, None)
        _ring_locked().append(doc)
    if tracing_enabled():
        args: Dict[str, Any] = {"trace_id": ctx.trace_id,
                                "outcome": outcome}
        if ctx.tenant is not None:
            args["tenant"] = ctx.tenant
        trace_complete("serve.request", total, cat="serve", args=args)
    return doc


def annotate_inflight(kind: str,
                      args: Optional[Dict[str, Any]] = None) -> int:
    """Stamp a concurrent event onto every in-flight request AND the
    process event ring (the swap/evict/breaker history post-mortem
    bundles archive). Returns the number of requests annotated. Cheap
    when idle: one empty-dict probe."""
    if not _inflight and not reqtrace_enabled():
        return 0
    with _lock:
        ctxs = list(_inflight.values())
        ev: Dict[str, Any] = {"kind": str(kind), "t_unix": time.time()}
        if args:
            ev["args"] = dict(args)
        _events.append(ev)
    for c in ctxs:
        c.annotate(kind, args)
    return len(ctxs)


def recent_events(n: Optional[int] = None) -> List[Dict[str, Any]]:
    """Newest-last slice of the process event ring."""
    with _lock:
        evs = list(_events)
    return evs if n is None else evs[-int(n):]


def recent(n: Optional[int] = None, tenant: Optional[str] = None,
           trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Newest-first finished-request documents, optionally filtered."""
    with _lock:
        docs = list(_ring)
    docs.reverse()
    if tenant is not None:
        docs = [d for d in docs if d.get("tenant") == tenant]
    if trace_id is not None:
        docs = [d for d in docs if d.get("trace_id") == trace_id]
    return docs if n is None else docs[:int(n)]


def find(trace_id: str) -> Optional[Dict[str, Any]]:
    """One request document by id (finished ring first, then the live
    in-flight set)."""
    with _lock:
        for d in reversed(_ring):
            if d.get("trace_id") == trace_id:
                return d
        ctx = _inflight.get(trace_id)
    return ctx.to_doc() if ctx is not None else None


def inflight_docs() -> List[Dict[str, Any]]:
    """Snapshots of the requests in flight right now (post-mortem
    bundles include them — the requests the incident caught mid-air)."""
    with _lock:
        ctxs = list(_inflight.values())
    return [c.to_doc() for c in ctxs]


def reset() -> None:
    """Test hook: clear the in-flight set, ring, and event history."""
    with _lock:
        _inflight.clear()
        _ring.clear()
        _events.clear()


# -- the batch-phase channel ----------------------------------------------
# The predictor's _predict_chunk knows encode/dispatch/device/decode
# boundaries but not which requests ride the batch; the server knows
# the requests but not the chunk internals. A contextvar bridges them
# without threading a parameter through every dispatch layer.

_batch_var: contextvars.ContextVar[Tuple[RequestContext, ...]] = \
    contextvars.ContextVar("alink_reqtrace_batch", default=())


@contextlib.contextmanager
def batch_scope(ctxs: List[Optional[RequestContext]]) -> Iterator[None]:
    """Bind the requests riding the current dispatch so
    :func:`batch_mark` inside the predictor stamps all of them."""
    token = _batch_var.set(tuple(c for c in ctxs if c is not None))
    try:
        yield
    finally:
        _batch_var.reset(token)


def batch_mark(phase: str) -> None:
    """Mark a phase boundary on every request in the active batch
    scope (no-op outside one — direct ``predict_table`` callers)."""
    for c in _batch_var.get():
        c.mark(phase)


# -- exemplar resolution --------------------------------------------------

def p99_exemplar(rec: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The exemplar of the bucket a histogram-snapshot record's p99
    falls in (the nearest lower bucket's when that bucket never caught
    one) — how a p99 number resolves to a concrete request timeline."""
    counts = rec.get("counts") or []
    total = sum(counts)
    if not total:
        return None
    exemplars = rec.get("exemplars") or []
    target = 0.99 * total
    cum = 0
    for i, c in enumerate(counts):
        cum += c
        if cum >= target:
            for j in range(i, -1, -1):
                if j < len(exemplars) and exemplars[j]:
                    return exemplars[j]
            return None
    return None
