"""DONATE-USE-AFTER positive: ``z`` is passed at a donate_argnums
position and read again afterwards — the donated buffer is dead after
the call ('Array has been deleted', or garbage on backends that skip
the runtime check)."""
import jax


def _step_factory():
    def fn(x, y, z):
        return z + x * y

    return jax.jit(fn, donate_argnums=(2,))


def train(x, y, z):
    step = _step_factory()
    out = step(x, y, z)
    return out + z.sum()          # read after donation: flagged


def train_wrapped(x, y, z):
    """Routing the step through a pass-through telemetry wrapper (the
    FTRL drain's ``run_step`` shape) must not blind the rule: the
    donated position shifts one right past the callable argument."""
    step = _step_factory()

    def run_step(fn, *args):
        return fn(*args)

    out = run_step(step, x, y, z)
    return out + z.sum()          # read after wrapped donation: flagged
