"""NaiveBayes text classification — mirror of the reference
``pyalink/review_naive_bayes.ipynb`` notebook (segment -> stopwords ->
count vectorize -> NaiveBayesText over review text), with a synthetic
review fixture instead of the hosted CSV (no egress).

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
     python examples/naive_bayes_example.py
"""

try:
    import _bootstrap  # noqa: F401  (repo root onto sys.path)
except ImportError:  # running as a module: python -m examples.foo
    from . import _bootstrap  # noqa: F401

import numpy as np

from alink_tpu.common.mlenv import use_local_env
from alink_tpu.operator.batch.evaluation import EvalBinaryClassBatchOp
from alink_tpu.operator.batch.source import MemSourceBatchOp
from alink_tpu.pipeline import Pipeline
from alink_tpu.pipeline.fm_nb import NaiveBayesTextClassifier
from alink_tpu.pipeline.nlp import DocCountVectorizer, Tokenizer

POS = ["great", "excellent", "love", "perfect", "amazing", "wonderful",
       "best", "comfortable", "recommend", "happy"]
NEG = ["terrible", "awful", "hate", "broken", "refund", "worst",
       "disappointed", "cheap", "return", "bad"]
FILLER = ["the", "product", "delivery", "box", "color", "size", "price",
          "store", "ordered", "arrived"]


def reviews(n: int = 800, seed: int = 11):
    rng = np.random.RandomState(seed)
    rows = []
    for _ in range(n):
        y = int(rng.rand() < 0.5)
        vocab = POS if y else NEG
        words = ([vocab[rng.randint(len(vocab))] for _ in range(rng.randint(2, 6))] +
                 [FILLER[rng.randint(len(FILLER))] for _ in range(rng.randint(3, 8))])
        rng.shuffle(words)
        rows.append((" ".join(words), y))
    return rows


def main():
    use_local_env()   # all available devices (8 on the CPU test mesh)
    rows = reviews()
    split = int(len(rows) * 0.8)
    train = MemSourceBatchOp(rows[:split], "review STRING, label INT")
    test = MemSourceBatchOp(rows[split:], "review STRING, label INT")

    pipe = Pipeline(
        Tokenizer(selected_col="review", output_col="words"),
        DocCountVectorizer(selected_col="words", output_col="vec"),
        NaiveBayesTextClassifier(vector_col="vec", label_col="label",
                                 prediction_col="pred",
                                 prediction_detail_col="detail"),
    )
    model = pipe.fit(train)
    pred = model.transform(test)
    metrics = (EvalBinaryClassBatchOp(label_col="label",
                                      prediction_detail_col="detail")
               .link_from(pred).collect_metrics())
    print("AUC:", metrics.get("AUC"), "Accuracy:", metrics.get("Accuracy"))
    assert metrics.get("AUC") > 0.95


if __name__ == "__main__":
    main()
