"""Request-scoped tracing, tail-latency exemplars, and post-mortem
bundles (ISSUE 18) — Layer 6 of the observability stack.

The load-bearing invariants:
  * every request served end-to-end carries the full mark chain
    (admit -> dequeue -> coalesce -> dispatch -> device -> decode) and
    the p99 exemplar of the request/queue-wait histograms resolves to
    one of those timelines — a tail number is a *request*, not just a
    bucket count;
  * concurrent swap/breaker events annotate overlapping in-flight
    requests (bounded per request), and land in the process event ring
    that bundles archive;
  * a multi-tenant fleet storm never bleeds one tenant's exemplar or
    timeline into another tenant's view;
  * lowered HLO and program-cache hit counts are BYTE-IDENTICAL with
    request tracing on vs off — Layer 6 is host-side only;
  * incident triggers (breaker open, injected kill) capture exactly
    ONE debounced, atomically-published bundle that doctor and trace
    render offline with nothing else on disk.
"""

import glob
import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import importlib.util

import numpy as np
import pytest

from alink_tpu.common import postmortem, reqtrace
from alink_tpu.common.adminz import AdminServer
from alink_tpu.common.faults import (FaultInjected, maybe_crash,
                                     reset_faults, scoped_fault_env)
from alink_tpu.common.metrics import MetricsRegistry, set_registry
from alink_tpu.common.mtable import MTable
from alink_tpu.common.params import Params
from alink_tpu.common.reqtrace import (MAX_ANNOTATIONS, RequestContext,
                                       p99_exemplar)
from alink_tpu.common.tracing import Tracer, set_tracer
from alink_tpu.common.vector import DenseVector
from alink_tpu.operator.batch.classification.linear import (
    LogisticRegressionTrainBatchOp)
from alink_tpu.operator.batch.source.sources import MemSourceBatchOp
from alink_tpu.operator.common.linear.mapper import LinearModelMapper
from alink_tpu.serving import (CompiledPredictor, FleetServer,
                               ModelRegistry, PredictServer)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FULL_MARKS = {"admit", "dequeue", "coalesce", "dispatch", "device",
              "decode"}


@pytest.fixture(autouse=True)
def clean_layer6():
    """Every test starts with empty rings and a fresh debounce clock."""
    reqtrace.reset()
    postmortem.reset_debounce()
    postmortem.clear_context()
    yield
    reqtrace.reset()
    postmortem.reset_debounce()
    postmortem.clear_context()


@pytest.fixture
def fresh_registry():
    reg = MetricsRegistry()
    prev = set_registry(reg)
    try:
        yield reg
    finally:
        set_registry(prev)


@pytest.fixture
def clean_faults(monkeypatch):
    reset_faults()
    yield monkeypatch
    monkeypatch.delenv("ALINK_TPU_FAULT_INJECT", raising=False)
    reset_faults()


@pytest.fixture(scope="module")
def base():
    rng = np.random.RandomState(3)
    n, d = 128, 10
    X = rng.randn(n, d)
    y = (X @ rng.randn(d) > 0).astype(np.int64)
    vecs = np.empty(n, object)
    vecs[:] = [DenseVector(X[i]) for i in range(n)]
    tbl = MTable({"vec": vecs, "label": y}, "vec VECTOR, label LONG")
    warm = LogisticRegressionTrainBatchOp(
        vector_col="vec", label_col="label",
        max_iter=2).link_from(MemSourceBatchOp(tbl))
    data_schema = tbl.select(["vec"]).schema
    mapper = LinearModelMapper(warm.get_output_table().schema, data_schema,
                               Params({"prediction_col": "pred",
                                       "vector_col": "vec"}))
    mapper.load_model(warm.get_output_table())
    return tbl, warm, mapper, data_schema


def _get(url, path):
    try:
        with urllib.request.urlopen(url + path, timeout=10) as r:
            return r.status, r.read().decode("utf-8")
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode("utf-8")


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_reqtrace_t", os.path.join(ROOT, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _hist(reg, name):
    return [r for r in reg.snapshot() if r["name"] == name]


# -- the context substrate ---------------------------------------------------

class TestRequestContext:
    def test_mark_chain_becomes_named_phases(self):
        ctx = RequestContext("r1", tenant="acme")
        for m in ("dequeue", "coalesce", "dispatch", "device", "decode"):
            ctx.mark(m)
        doc = ctx.to_doc(total_s=ctx.elapsed_s())
        assert [m["phase"] for m in doc["marks"]] == \
            ["admit", "dequeue", "coalesce", "dispatch", "device",
             "decode"]
        # the queue phase is named by its ENDING mark (dequeue); every
        # other phase carries its own mark's name
        assert set(doc["phases"]) == {"queue_s", "coalesce_s",
                                      "dispatch_s", "device_s",
                                      "decode_s"}
        assert doc["tenant"] == "acme"
        assert doc["total_s"] >= doc["marks"][-1]["t_s"]
        # offsets are monotonic from admission (t=0)
        ts = [m["t_s"] for m in doc["marks"]]
        assert ts[0] == 0.0 and ts == sorted(ts)

    def test_annotations_bounded_with_overflow_count(self):
        ctx = RequestContext("r2")
        for i in range(MAX_ANNOTATIONS + 5):
            ctx.annotate("swap", {"version": i})
        assert len(ctx.annotations) == MAX_ANNOTATIONS
        assert ctx.dropped_annotations == 5
        assert ctx.to_doc()["dropped_annotations"] == 5

    def test_ring_respects_flag_capacity(self, monkeypatch):
        monkeypatch.setenv("ALINK_TPU_REQTRACE_RING", "4")
        ids = []
        for _ in range(10):
            ctx = reqtrace.admit()
            ids.append(ctx.trace_id)
            reqtrace.finish(ctx)
        docs = reqtrace.recent()
        assert len(docs) == 4
        # newest first, and the survivors are the LAST four finished
        assert [d["trace_id"] for d in docs] == ids[-1:-5:-1]
        assert reqtrace.find(ids[0]) is None
        assert reqtrace.find(ids[-1])["trace_id"] == ids[-1]

    def test_off_switch_mints_nothing(self, monkeypatch):
        monkeypatch.setenv("ALINK_TPU_REQTRACE", "0")
        assert reqtrace.admit() is None
        assert reqtrace.finish(None) is None
        assert reqtrace.recent() == []

    def test_annotate_inflight_stamps_live_requests_and_event_ring(self):
        ctx = reqtrace.admit(tenant="a")
        done = reqtrace.admit(tenant="b")
        reqtrace.finish(done)
        n = reqtrace.annotate_inflight("evict", {"tenant": "c",
                                                 "bytes": 128})
        assert n == 1                      # only the in-flight request
        assert ctx.annotations[0]["kind"] == "evict"
        assert ctx.annotations[0]["args"]["bytes"] == 128
        evs = reqtrace.recent_events()
        assert evs and evs[-1]["kind"] == "evict"
        # the finished request never saw it
        assert reqtrace.find(done.trace_id)["annotations"] == []

    def test_p99_exemplar_lower_bucket_fallback(self):
        rec = {"buckets": [0.1, 1.0], "counts": [10, 0, 1],
               "exemplars": [{"trace_id": "rA", "value": 0.05},
                             None, None]}
        # p99 falls in the +Inf bucket, which never caught an exemplar
        # — the nearest LOWER bucket's exemplar still names a request
        assert p99_exemplar(rec)["trace_id"] == "rA"
        assert p99_exemplar({"buckets": [], "counts": [],
                             "exemplars": []}) is None


# -- the serving path end-to-end ---------------------------------------------

class TestServerTimeline:
    def test_full_timeline_and_p99_exemplar_resolve(self, base,
                                                    fresh_registry):
        tbl, _w, mapper, _s = base
        req = tbl.select(["vec"])
        srv = PredictServer(CompiledPredictor(mapper, buckets=(1, 4)),
                            name="tl")
        try:
            for f in [srv.submit(req.row(i)) for i in range(12)]:
                f.result(60)
        finally:
            srv.close()
        docs = reqtrace.recent()
        assert len(docs) == 12
        for d in docs:
            assert {m["phase"] for m in d["marks"]} >= FULL_MARKS
            assert d["outcome"] == "ok"
            assert set(d["phases"]) >= {"queue_s", "coalesce_s",
                                        "dispatch_s", "device_s",
                                        "decode_s"}
        # both histograms observed every request, labeled by server
        for name in ("alink_serve_request_seconds",
                     "alink_serve_queue_wait_seconds"):
            recs = _hist(fresh_registry, name)
            assert len(recs) == 1, name
            assert recs[0]["labels"] == {"server": "tl"}
            assert recs[0]["count"] == 12
            # the p99 exemplar resolves to a full captured timeline
            ex = p99_exemplar(recs[0])
            assert ex is not None and "trace_id" in ex
            doc = reqtrace.find(ex["trace_id"])
            assert doc is not None
            assert {m["phase"] for m in doc["marks"]} >= FULL_MARKS

    def test_exemplars_round_trip_snapshot_load(self, base,
                                                fresh_registry,
                                                tmp_path):
        tbl, _w, mapper, _s = base
        req = tbl.select(["vec"])
        srv = PredictServer(CompiledPredictor(mapper, buckets=(1,)),
                            name="rt")
        try:
            srv.submit(req.row(0)).result(60)
        finally:
            srv.close()
        p = tmp_path / "metrics.json"
        fresh_registry.dump(str(p))
        reloaded = MetricsRegistry.load(str(p))
        rec = _hist(reloaded, "alink_serve_request_seconds")[0]
        assert p99_exemplar(rec)["trace_id"] == \
            p99_exemplar(_hist(fresh_registry,
                               "alink_serve_request_seconds")[0]
                         )["trace_id"]

    def test_swap_annotates_overlapping_request(self, base,
                                                fresh_registry):
        tbl, warm, mapper, _s = base
        srv = PredictServer(CompiledPredictor(mapper, buckets=(1, 4)),
                            name="sw")
        try:
            # a request admitted but never dispatched IS in flight —
            # the swap flip must stamp its timeline deterministically
            ctx = reqtrace.admit()
            srv.swap_model(warm.get_output_table())
            kinds = [a["kind"] for a in ctx.annotations]
            assert "swap" in kinds
            evs = [e for e in reqtrace.recent_events()
                   if e["kind"] == "swap"]
            assert evs and evs[-1]["args"]["version"] == 2
            reqtrace.finish(ctx)
            assert "swap" in [a["kind"] for a in
                              reqtrace.find(ctx.trace_id)["annotations"]]
        finally:
            srv.close()


# -- multi-tenant isolation ---------------------------------------------------

class TestFleetIsolation:
    def test_storm_has_no_cross_tenant_bleed(self, base, fresh_registry,
                                             tmp_path):
        import copy
        tbl, _w, mapper, _s = base
        req = tbl.select(["vec"])
        tenants = {}
        for i in range(4):
            m = copy.deepcopy(mapper)
            r = np.random.RandomState(500 + i)
            m.model.coef = np.asarray(m.model.coef) \
                + 0.05 * r.randn(*np.shape(m.model.coef))
            tenants[f"t{i}"] = m
        registry = ModelRegistry(snapshot_dir=str(tmp_path),
                                 buckets=(1, 4), name="iso")
        for tid, m in tenants.items():
            registry.register(tid, m)
        srv = FleetServer(registry, min_fill=4, window_s=0.002,
                          name="iso")
        per_tenant = 8
        try:
            futs = [(tid, srv.submit(tid, req.row(i)))
                    for i in range(per_tenant)
                    for tid in tenants]
            for _tid, f in futs:
                f.result(60)
        finally:
            srv.close()
        # every finished timeline carries exactly its own tenant, with
        # the full mark chain even through the coalesced path
        for tid in tenants:
            docs = reqtrace.recent(tenant=tid)
            assert len(docs) == per_tenant, tid
            for d in docs:
                assert d["tenant"] == tid
                assert {m["phase"] for m in d["marks"]} >= FULL_MARKS
        # exemplar bleed check: each histogram exemplar's tenant tag
        # must match the tenant of the timeline its trace_id names
        checked = 0
        for name in ("alink_serve_request_seconds",
                     "alink_serve_queue_wait_seconds"):
            for rec in _hist(fresh_registry, name):
                for ex in (rec.get("exemplars") or []):
                    if not ex:
                        continue
                    doc = reqtrace.find(ex["trace_id"])
                    assert doc is not None
                    assert doc["tenant"] == ex["tenant"], (name, ex)
                    checked += 1
        assert checked > 0

    def test_shed_and_rejected_outcomes_are_typed(self, base,
                                                  fresh_registry,
                                                  tmp_path):
        tbl, _w, mapper, _s = base
        req = tbl.select(["vec"])
        registry = ModelRegistry(snapshot_dir=str(tmp_path),
                                 buckets=(1,), name="shed")
        registry.register("t0", mapper)
        srv = FleetServer(registry, name="shed")
        try:
            f = srv.submit("t0", req.row(0), deadline_s=0.0)
            with pytest.raises(Exception):
                f.result(60)
        finally:
            srv.close()
        outcomes = {d["outcome"] for d in reqtrace.recent()}
        assert any(o.startswith("shed_") or o == "ok" for o in outcomes)
        # nothing is left dangling in the in-flight set after close
        assert reqtrace.inflight_docs() == []


# -- zero compiled ops --------------------------------------------------------

class TestZeroCompiledOps:
    def test_lowered_hlo_identical_on_off(self, monkeypatch):
        import jax
        import jax.numpy as jnp

        def fn(x):
            return (x @ x).sum()

        x = jnp.ones((16, 16), jnp.float32)
        monkeypatch.setenv("ALINK_TPU_REQTRACE", "0")
        off = jax.jit(fn).lower(x).as_text()
        monkeypatch.setenv("ALINK_TPU_REQTRACE", "1")
        ctxs = [reqtrace.admit() for _ in range(4)]
        with reqtrace.batch_scope(ctxs):
            reqtrace.batch_mark("dispatch")
            on = jax.jit(fn).lower(x).as_text()
        for c in ctxs:
            reqtrace.finish(c)
        assert on == off
        low = on.lower()
        assert "callback" not in low and "outfeed" not in low

    def test_program_cache_hits_identical_on_off(self, base,
                                                 fresh_registry,
                                                 monkeypatch):
        tbl, _w, mapper, _s = base
        probe = tbl.select(["vec"]).first_n(4)

        def run():
            srv = PredictServer(CompiledPredictor(mapper, buckets=(4,),
                                                  name="zc"),
                                name="zc")
            try:
                for _ in range(3):
                    for f in [srv.submit(probe.row(i)) for i in range(4)]:
                        f.result(60)
                return srv.predictor.cache_stats()
            finally:
                srv.close()

        monkeypatch.setenv("ALINK_TPU_REQTRACE", "0")
        stats_off = run()
        reqtrace.reset()
        monkeypatch.setenv("ALINK_TPU_REQTRACE", "1")
        stats_on = run()
        assert stats_on == stats_off
        assert stats_on["hits"] >= 1
        # and the requests really were traced in the ON run
        assert len(reqtrace.recent()) == 12


# -- post-mortem bundles ------------------------------------------------------

class TestPostmortem:
    def test_bundle_contents_and_debounce(self, base, fresh_registry,
                                          monkeypatch, tmp_path):
        monkeypatch.setenv("ALINK_TPU_POSTMORTEM_DIR", str(tmp_path))
        tbl, _w, mapper, _s = base
        req = tbl.select(["vec"])
        srv = PredictServer(CompiledPredictor(mapper, buckets=(1,)),
                            name="pm")
        try:
            for f in [srv.submit(req.row(i)) for i in range(4)]:
                f.result(60)
        finally:
            srv.close()
        postmortem.set_context("checkpoint", "/ckpt/42")
        path = postmortem.maybe_bundle("breaker_open", "unit trigger",
                                       extra={"step": 2})
        assert path is not None and os.path.exists(path)
        # debounced: a cascading second trigger writes NOTHING
        assert postmortem.maybe_bundle("slo_burn", "cascade") is None
        files = os.listdir(str(tmp_path))
        assert len(files) == 1 and not any(f.endswith(".tmp")
                                           for f in files)
        doc = postmortem.load_bundle(path)
        assert doc["format"] == postmortem.BUNDLE_FORMAT
        assert doc["reason"] == "breaker_open"
        assert doc["detail"] == "unit trigger"
        assert doc["extra"] == {"step": 2}
        assert doc["context"]["checkpoint"] == "/ckpt/42"
        assert len(doc["requests"]) == 4
        assert {m["phase"] for m in doc["requests"][0]["marks"]} \
            >= FULL_MARKS
        assert doc["flags"].get("ALINK_TPU_REQTRACE") is True
        assert any(r["name"] == "alink_serve_request_seconds"
                   for r in doc["metrics"])
        # the suppressed cascade is countable
        assert any(r["name"] == "alink_postmortem_suppressed_total"
                   for r in fresh_registry.snapshot())

    def test_debounce_window_and_retention(self, monkeypatch, tmp_path):
        monkeypatch.setenv("ALINK_TPU_POSTMORTEM_DIR", str(tmp_path))
        monkeypatch.setenv("ALINK_TPU_POSTMORTEM_DEBOUNCE_S", "0")
        monkeypatch.setenv("ALINK_TPU_POSTMORTEM_KEEP", "2")
        paths = [postmortem.maybe_bundle(f"r{i}") for i in range(4)]
        assert all(p is not None for p in paths)
        left = sorted(os.listdir(str(tmp_path)))
        assert len(left) == 2
        # retention keeps the NEWEST bundles
        assert os.path.basename(paths[-1]) in left

    def test_breaker_open_storm_writes_one_bundle(self, base,
                                                  fresh_registry,
                                                  clean_faults,
                                                  tmp_path):
        clean_faults.setenv("ALINK_TPU_POSTMORTEM_DIR", str(tmp_path))
        tbl, _w, mapper, _s = base
        req = tbl.select(["vec"])
        srv = PredictServer(CompiledPredictor(mapper, buckets=(1,)),
                            name="pmb")
        try:
            srv.submit(req.row(0)).result(60)
            with scoped_fault_env("serve.dispatch:1-8:error"):
                for i in range(8):      # closed loop: no coalescing
                    try:
                        srv.submit(req.row(i)).result(60)
                    except Exception:
                        pass
        finally:
            srv.close()
        bundles = glob.glob(os.path.join(str(tmp_path),
                                         "postmortem_*.json"))
        assert len(bundles) == 1
        doc = postmortem.load_bundle(bundles[0])
        assert doc["reason"] == "breaker_open"
        # requests in flight across the OPEN transition carry the
        # breaker event on their timelines OR the event ring holds it
        assert any(e["kind"] == "breaker"
                   for e in doc["events"])

    def test_injected_kill_writes_bundle(self, clean_faults, tmp_path):
        clean_faults.setenv("ALINK_TPU_POSTMORTEM_DIR", str(tmp_path))
        with scoped_fault_env("unit.kill:1-1:kill"):
            with pytest.raises(FaultInjected):
                maybe_crash("unit.kill")
        bundles = glob.glob(os.path.join(str(tmp_path),
                                         "postmortem_*.json"))
        assert len(bundles) == 1
        doc = postmortem.load_bundle(bundles[0])
        assert doc["reason"] == "injected_kill"
        assert doc["extra"]["site"] == "unit.kill"

    def test_unarmed_dir_writes_nothing(self, monkeypatch, tmp_path):
        monkeypatch.delenv("ALINK_TPU_POSTMORTEM_DIR", raising=False)
        assert postmortem.maybe_bundle("breaker_open") is None


# -- offline rendering (doctor + trace) ---------------------------------------

class TestOfflineRendering:
    def _bundle(self, base, tmp_path, monkeypatch):
        monkeypatch.setenv("ALINK_TPU_POSTMORTEM_DIR", str(tmp_path))
        tbl, _w, mapper, _s = base
        req = tbl.select(["vec"])
        srv = PredictServer(CompiledPredictor(mapper, buckets=(1,)),
                            name="od")
        try:
            for f in [srv.submit(req.row(i)) for i in range(6)]:
                f.result(60)
        finally:
            srv.close()
        path = postmortem.maybe_bundle("slo_burn", "offline fixture")
        assert path is not None
        return path, reqtrace.recent()[0]["trace_id"]

    def test_doctor_renders_verdict_from_bundle_alone(
            self, base, fresh_registry, monkeypatch, tmp_path):
        path, _tid = self._bundle(base, tmp_path, monkeypatch)
        out = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "doctor.py"),
             "--bundle", path],
            capture_output=True, text=True, cwd=ROOT, timeout=120)
        assert out.returncode == 0, out.stderr
        assert "post-mortem: slo_burn" in out.stdout
        assert "verdict:" in out.stdout
        assert "request timelines" in out.stdout
        # the doctor re-summarizes the bundled metrics dump offline
        assert "queue" in out.stdout

    def test_trace_renders_one_request_lifetime(self, base,
                                                fresh_registry,
                                                monkeypatch, tmp_path):
        path, tid = self._bundle(base, tmp_path, monkeypatch)
        trace = _load_tool("trace")
        meta, events = trace.load_events(path)
        text = trace.render_request(meta, events, tid)
        assert text is not None
        assert f"request {tid}" in text
        for mark in ("admit", "dequeue", "dispatch", "decode"):
            assert mark in text
        # an id the bundle never saw renders nothing
        assert trace.render_request(meta, events, "r99999999") is None

    def test_doctor_rejects_wrong_format(self, tmp_path):
        bad = tmp_path / "not_a_bundle.json"
        bad.write_text(json.dumps({"format": "something_else"}))
        out = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "doctor.py"),
             "--bundle", str(bad)],
            capture_output=True, text=True, cwd=ROOT, timeout=120)
        assert out.returncode != 0


# -- the admin plane ----------------------------------------------------------

class TestAdminEndpoints:
    def test_requestz_serves_filtered_timelines(self, fresh_registry):
        for i in range(6):
            ctx = reqtrace.admit(tenant="a" if i % 2 else "b")
            ctx.mark("dequeue")
            reqtrace.finish(ctx)
        live = reqtrace.admit(tenant="a")
        with AdminServer(port=-1).start() as srv:
            code, text = _get(srv.url, "/requestz")
            assert code == 200
            doc = json.loads(text)
            assert doc["enabled"] is True
            assert doc["returned"] == 6
            assert len(doc["inflight"]) == 1
            assert doc["inflight"][0]["trace_id"] == live.trace_id
            # tenant filter
            _, text = _get(srv.url, "/requestz?tenant=a")
            doc_a = json.loads(text)
            assert all(r["tenant"] == "a" for r in doc_a["requests"])
            assert len(doc_a["inflight"]) == 1
            # n= narrows; trace_id= pinpoints
            _, text = _get(srv.url, "/requestz?n=2")
            assert len(json.loads(text)["requests"]) == 2
            tid = doc["requests"][0]["trace_id"]
            _, text = _get(srv.url, f"/requestz?trace_id={tid}")
            got = json.loads(text)["requests"]
            assert len(got) == 1 and got[0]["trace_id"] == tid
        reqtrace.finish(live)

    def test_requestz_clamped_by_flag(self, fresh_registry, monkeypatch):
        monkeypatch.setenv("ALINK_TPU_ADMIN_REQUESTZ", "3")
        for _ in range(10):
            reqtrace.finish(reqtrace.admit())
        with AdminServer(port=-1).start() as srv:
            _, text = _get(srv.url, "/requestz?n=50")
            assert len(json.loads(text)["requests"]) == 3

    def test_tracez_filters_by_trace_id(self, fresh_registry,
                                        monkeypatch):
        monkeypatch.setenv("ALINK_TPU_TRACE", "1")
        tr = Tracer(capacity=64)
        prev = set_tracer(tr)
        try:
            ids = []
            for _ in range(5):
                ctx = reqtrace.admit()
                ids.append(ctx.trace_id)
                reqtrace.finish(ctx)
            with AdminServer(port=-1).start() as srv:
                code, text = _get(srv.url,
                                  f"/tracez?trace_id={ids[2]}")
                assert code == 200
                doc = json.loads(text)
                assert doc["trace_id"] == ids[2]
                assert doc["events"], "no serve.request event captured"
                for e in doc["events"]:
                    assert e["args"]["trace_id"] == ids[2]
                # unfiltered view still carries every request's event
                _, text = _get(srv.url, "/tracez")
                allv = json.loads(text)
                got = {e["args"]["trace_id"] for e in allv["events"]
                       if (e.get("args") or {}).get("trace_id")}
                assert set(ids) <= got
        finally:
            set_tracer(prev)
