"""Bounded prefetch for stream drains — host/device pipelining.

The Flink reference runs every stream operator as its own pipelined task:
while FtrlTrainStreamOp's CalcTask crunches batch t, the upstream hash /
parse operators are already producing batch t+1
(FtrlTrainStreamOp.java:120-135). The round-2 runtime was a single lazy
generator chain, so host encode and device compute ran strictly
back-to-back (VERDICT r2 #4).

``prefetch(it, depth)`` runs the upstream iterator in ONE background
thread feeding a bounded queue: the main thread dispatches device steps
for item t while the thread parses/hashes/pads item t+1. A FIFO queue
preserves order exactly (test_stream.py proves no reordering), the bound
gives backpressure (the thread blocks when the consumer falls behind —
Flink's bounded exchange buffers), and upstream exceptions re-raise at
the consumption point. Per-sample order INSIDE a batch is untouched, so
strict-FTRL semantics are unchanged.

``ALINK_TPU_STREAM_PREFETCH`` — depth override; "0" disables (inline
iteration), unset means depth 2.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Iterable, Iterator, TypeVar

T = TypeVar("T")

_SENTINEL = object()


def prefetch_depth(default: int = 2) -> int:
    v = os.environ.get("ALINK_TPU_STREAM_PREFETCH", "")
    if v == "":
        return default
    return max(0, int(v))


def prefetch(it: Iterable[T], depth: int = None) -> Iterator[T]:
    """Iterate ``it`` in a background thread, ``depth`` items ahead."""
    depth = prefetch_depth() if depth is None else depth
    if depth <= 0:
        yield from it
        return
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    err: list = []
    stop = threading.Event()

    def put(item) -> bool:
        """Bounded put that gives up when the consumer has abandoned the
        stream — a bare q.put would block forever on a full queue."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in it:
                if not put(item):
                    break
        except BaseException as e:  # propagate to the consumer
            err.append(e)
        finally:
            # close the upstream generator on EVERY exit path (normal end,
            # upstream error, consumer abandonment) and BEFORE the
            # sentinel, so a failing flush-on-close still reaches the
            # consumer instead of dying on the daemon thread
            try:
                close = getattr(it, "close", None)
                if close is not None:
                    close()
            except BaseException as e:
                err.append(e)
            put(_SENTINEL)

    th = threading.Thread(target=worker, daemon=True,
                          name="alink-stream-prefetch")
    th.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        # consumer abandoned early (STOP sentinel downstream, exception):
        # signal the producer to stop, then drain so an in-flight put
        # returns immediately
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        th.join(timeout=5.0)
        if th.is_alive():
            # the producer is stuck inside the upstream iterator itself
            # (e.g. a blocking poll) — it cannot see the stop flag until
            # that call returns, so the daemon thread outlives us still
            # holding the iterator. Make that diagnosable, not silent.
            import logging
            logging.getLogger(__name__).warning(
                "prefetch worker did not exit within 5s of consumer "
                "abandonment; the upstream source appears blocked")
