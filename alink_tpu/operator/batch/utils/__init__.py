from .fn_ops import (DataSetWrapperBatchOp, FlatMapBatchOp, PrintBatchOp,
                     UDFBatchOp, UDTFBatchOp)
from .model_map import MapBatchOp, ModelMapBatchOp
