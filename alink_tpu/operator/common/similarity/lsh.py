"""Locality-sensitive hashing for approximate vector similarity joins.

Re-design of common/feature/BaseLSH + MinHashLSH + BucketRandomProjectionLSH
and batch/similarity/ ApproxVectorSimilarityJoinLSHBatchOp / TopNLSHBatchOp.

TPU-first: hashing and the candidate re-scoring are batched device matmuls
(projections are one (n, d) @ (d, h) on the MXU; candidate distances are
batched gathers + norms); only the bucket grouping is host-side hashing.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ....common.mtable import MTable
from ....common.vector import DenseVector, SparseVector, VectorUtil


def _to_dense(vecs, dim: Optional[int] = None) -> np.ndarray:
    parsed = [VectorUtil.parse(v) for v in vecs]
    if dim is None:
        dim = 0
        for v in parsed:
            dim = max(dim, v.size() if isinstance(v, DenseVector)
                      else (v.n if v.n >= 0 else int(v.indices[-1]) + 1))
    X = np.zeros((len(parsed), dim))
    for i, v in enumerate(parsed):
        if isinstance(v, DenseVector):
            X[i, :v.size()] = v.data
        else:
            X[i, v.indices.astype(int)] = v.values
    return X


class BucketRandomProjectionLSH:
    """Euclidean-distance LSH: h(x) = floor((x·w + b) / bucket_width)
    (reference common/feature/BucketRandomProjectionLSH)."""

    def __init__(self, dim: int, num_projections: int = 10,
                 num_hash_tables: int = 2, bucket_width: float = 1.0,
                 seed: int = 0):
        rng = np.random.RandomState(seed)
        self.W = rng.randn(dim, num_hash_tables * num_projections)
        self.b = rng.rand(num_hash_tables * num_projections) * bucket_width
        self.bucket_width = bucket_width
        self.num_tables = num_hash_tables
        self.num_proj = num_projections

    def hash(self, X: np.ndarray) -> np.ndarray:
        """(n, tables, proj) integer bucket ids — one device matmul."""
        import jax.numpy as jnp
        H = np.asarray(jnp.floor((jnp.asarray(X) @ self.W + self.b)
                                 / self.bucket_width), np.int64)
        return H.reshape(X.shape[0], self.num_tables, self.num_proj)

    def keys(self, X: np.ndarray) -> List[List[Tuple]]:
        H = self.hash(X)
        return [[tuple(H[i, t]) for t in range(self.num_tables)]
                for i in range(X.shape[0])]

    @staticmethod
    def distance(a: np.ndarray, B: np.ndarray) -> np.ndarray:
        return np.linalg.norm(B - a, axis=-1)


class MinHashLSH:
    """Jaccard-distance LSH over the non-zero index set
    (reference common/feature/MinHashLSH)."""

    PRIME = (1 << 31) - 1

    def __init__(self, num_hash: int = 16, num_bands: int = 4, seed: int = 0):
        rng = np.random.RandomState(seed)
        self.a = rng.randint(1, self.PRIME, size=num_hash).astype(np.int64)
        self.b = rng.randint(0, self.PRIME, size=num_hash).astype(np.int64)
        self.num_hash = num_hash
        self.num_bands = num_bands

    def signature(self, active: Sequence[int]) -> np.ndarray:
        if len(active) == 0:
            return np.full(self.num_hash, self.PRIME, np.int64)
        idx = np.asarray(list(active), np.int64)[:, None]
        h = (self.a * (idx + 1) + self.b) % self.PRIME
        return h.min(axis=0)

    def keys_for(self, active: Sequence[int]) -> List[Tuple]:
        sig = self.signature(active)
        per = max(1, self.num_hash // self.num_bands)
        return [tuple(sig[t * per:(t + 1) * per]) for t in range(self.num_bands)]

    @staticmethod
    def jaccard_dist(a: set, b: set) -> float:
        if not a and not b:
            return 0.0
        u = len(a | b)
        return 1.0 - (len(a & b) / u if u else 0.0)


def approx_join(left: MTable, right: MTable, left_col: str, right_col: str,
                left_id: str, right_id: str, threshold: float,
                metric: str = "EUCLIDEAN", top_n: Optional[int] = None,
                seed: int = 0, **lsh_kw) -> List[Tuple]:
    """Candidate pairs via shared LSH buckets, exact re-score, filter.

    Returns rows (left_id, right_id, distance). ``top_n`` keeps the N
    nearest rights per left (TopN variant); otherwise threshold filter
    (Join variant).
    """
    lv, rv = left.col(left_col), right.col(right_col)
    if metric.upper() == "JACCARD":
        lsh = MinHashLSH(seed=seed, **lsh_kw)

        def active_set(x):
            v = VectorUtil.parse(x)
            if isinstance(v, SparseVector):
                return set(v.indices.astype(int))
            return set(np.nonzero(np.asarray(v.data))[0])

        lsets = [active_set(x) for x in lv]
        rsets = [active_set(x) for x in rv]
        buckets: Dict[Tuple, List[int]] = {}
        for j, s in enumerate(rsets):
            for t, key in enumerate(lsh.keys_for(s)):
                buckets.setdefault((t, key), []).append(j)
        out = []
        for i, s in enumerate(lsets):
            cands = set()
            for t, key in enumerate(lsh.keys_for(s)):
                cands.update(buckets.get((t, key), ()))
            scored = [(left.col(left_id)[i], right.col(right_id)[j],
                       lsh.jaccard_dist(s, rsets[j])) for j in cands]
            out.extend(_pick(scored, threshold, top_n))
        return out

    X, Y = _to_dense(lv), _to_dense(rv)
    d = max(X.shape[1], Y.shape[1])
    if X.shape[1] < d:
        X = np.pad(X, ((0, 0), (0, d - X.shape[1])))
    if Y.shape[1] < d:
        Y = np.pad(Y, ((0, 0), (0, d - Y.shape[1])))
    lsh = BucketRandomProjectionLSH(d, seed=seed, **lsh_kw)
    rkeys = lsh.keys(Y)
    buckets = {}
    for j, keys in enumerate(rkeys):
        for t, key in enumerate(keys):
            buckets.setdefault((t, key), []).append(j)
    lkeys = lsh.keys(X)
    out = []
    for i, keys in enumerate(lkeys):
        cands = set()
        for t, key in enumerate(keys):
            cands.update(buckets.get((t, key), ()))
        if not cands:
            continue
        js = sorted(cands)
        dist = lsh.distance(X[i], Y[js])
        scored = [(left.col(left_id)[i], right.col(right_id)[j], float(dv))
                  for j, dv in zip(js, dist)]
        out.extend(_pick(scored, threshold, top_n))
    return out


def _pick(scored: List[Tuple], threshold: float, top_n: Optional[int]):
    if top_n is not None:
        return sorted(scored, key=lambda r: r[2])[:top_n]
    return [r for r in scored if r[2] <= threshold]
