"""Communicate stages — XLA collectives over the device mesh.

The reference implements MPI-style primitives by hand over Flink shuffles:
  - AllReduce: 3-phase scatter(4096-chunk)/reduce/broadcast over two
    ``partitionCustom`` shuffles (communication/AllReduce.java:85-360).
  - broadcast: ``withBroadcastSet`` replication (BaseComQueue.java:337-369).
Here each primitive is ONE XLA collective over the ICI mesh (SURVEY §2.4):
psum / pmax / pmin / all_gather / ppermute. Chunking, routing and reassembly
belong to the compiler.

Telemetry: every communicate stage reports its invocation and logical
payload bytes through :func:`record_collective` **at trace time** (shapes
and dtypes are known on tracers; no host callback enters the compiled
program). The engine installs :func:`collecting` around superstep tracing
to capture a per-superstep manifest it later multiplies by the executed
superstep count; outside a collector the record lands directly in the
process ``MetricsRegistry`` (standalone use of these stages).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

import numpy as np

from ..common.metrics import get_registry, metrics_enabled
from .context import ComContext

# (collective_kind, buffer_name, logical_bytes_per_invocation) triples
CollectiveRecord = Tuple[str, str, int]

_collector = threading.local()


@contextlib.contextmanager
def collecting(manifest: List[CollectiveRecord]):
    """Route :func:`record_collective` calls on this thread into
    ``manifest`` (the engine's per-superstep trace capture) instead of the
    registry. Nests: the previous sink is restored on exit."""
    prev = getattr(_collector, "manifest", None)
    _collector.manifest = manifest
    try:
        yield manifest
    finally:
        _collector.manifest = prev


def payload_nbytes(value) -> int:
    """Logical payload bytes of a buffer pytree as seen by ONE worker
    (tracer-safe: reads only aval shape/dtype)."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(value):
        shape = getattr(leaf, "shape", ())
        dtype = getattr(leaf, "dtype", None)
        itemsize = np.dtype(dtype).itemsize if dtype is not None else 8
        n = 1
        for d in shape:
            n *= int(d)
        total += n * itemsize
    return total


def record_collective(kind: str, name: str, per_worker_bytes: int,
                      num_workers: int, members: Optional[Tuple[str, ...]]
                      = None) -> None:
    """Record one collective invocation. ``logical bytes moved`` is the
    payload summed over workers (every worker contributes/receives its
    copy), not the wire traffic of a particular ring schedule.

    ``members`` names the original buffers coalesced into this op when it
    is a FUSED collective (ALINK_TPU_FUSE_COLLECTIVES): the record becomes
    a 4-tuple carrying the fused-group membership, and the registry path
    additionally charges ``alink_collective_fused_total`` /
    ``alink_collective_payload_fused_bytes``."""
    logical = int(per_worker_bytes) * int(num_workers)
    fused = members is not None and len(members) > 1
    manifest = getattr(_collector, "manifest", None)
    if manifest is not None:
        if fused:
            manifest.append((kind, name, logical, tuple(members)))
        else:
            manifest.append((kind, name, logical))
        return
    if metrics_enabled():
        reg = get_registry()
        lbl = {"collective": kind}
        reg.inc("alink_collective_calls_total", 1, lbl)
        reg.inc("alink_collective_logical_bytes_total", logical, lbl)
        if fused:
            reg.inc("alink_collective_fused_total", 1, lbl)
            reg.inc("alink_collective_payload_fused_bytes", logical, lbl)


def record_manifest(manifest: Sequence[CollectiveRecord],
                    times: int = 1) -> None:
    """Charge a memoized trace-time manifest to the metrics registry.

    Collectives record at TRACE time, so inside a jit-cached program the
    records fire once per COMPILE, not once per call. The engine fixes
    this for comqueue programs by multiplying the per-superstep manifest
    by the executed superstep count; callers that invoke cached programs
    outside the engine (the FTRL drain loop) capture the program's
    manifest once (:func:`collecting` around an AOT ``.lower``) and
    replay it here per invocation, so ``alink_collective_calls_total``
    counts executed micro-batches rather than compiles.

    Records are 3-tuples ``(kind, name, bytes)`` or — for fused
    collectives — 4-tuples carrying the member-buffer names."""
    if not manifest or not metrics_enabled():
        return
    reg = get_registry()
    for rec in manifest:
        kind, logical = rec[0], rec[2]
        lbl = {"collective": kind}
        reg.inc("alink_collective_calls_total", times, lbl)
        reg.inc("alink_collective_logical_bytes_total",
                int(logical) * int(times), lbl)
        if len(rec) > 3 and len(rec[3]) > 1:
            reg.inc("alink_collective_fused_total", times, lbl)
            reg.inc("alink_collective_payload_fused_bytes",
                    int(logical) * int(times), lbl)


# -- trace-time collective fusion (ALINK_TPU_FUSE_COLLECTIVES) --------------
# One fused collective per superstep, where data flow allows it: inside a
# ``fusing()`` scope (the engine arms one around every superstep trace)
# the manifest wrappers below DEFER their reduction — the payload is
# registered with the scope's accumulator and the caller receives a
# :class:`_Deferred` proxy. The first *use* of any deferred value (a jnp
# op, indexing, an attribute read) flushes the whole accumulator: all
# same-reduction, same-dtype pending payloads are flattened, concatenated
# into one lane buffer, reduced by ONE ``lax`` collective, and bitwise-
# split back to the original buffers (all-reduce is elementwise, so each
# element's result is exactly the unfused op's). ``pmin`` payloads of
# inexact dtype ride the max lane negated (`min(x) == -max(-x)` is exact
# for floats — the sign flip never rounds).
#
# Flush-on-first-use is also the dependency PROOF: a collective whose
# input depends on an earlier collective's OUTPUT can only be registered
# after that output was used, i.e. after the earlier flush — so what ends
# up fused is exactly the set of independent collectives, and what stays
# separate is separated by real data flow (L-BFGS's line-loss psum needs
# the psummed gradient's direction; GBDT's level-L histogram needs the
# level-L-1 split). A scope with a single pending payload lowers the
# ORIGINAL payload through the raw op — byte-identical semantics to the
# unfused wrapper.

def fusion_enabled() -> bool:
    """``ALINK_TPU_FUSE_COLLECTIVES`` (default OFF): trace-time collective
    fusion. Folded into the engine program-cache key and (conditionally)
    checkpoint signatures — the fused program is structurally different
    HLO even though training results are bitwise-identical."""
    from ..common.flags import flag_value
    return bool(flag_value("ALINK_TPU_FUSE_COLLECTIVES"))


_fusion = threading.local()


def active_fusion_scope():
    """The installed :class:`_FusionScope` of this thread (None outside
    ``fusing()`` — wrappers then lower eagerly, the historical path)."""
    return getattr(_fusion, "scope", None)


class _Deferred:
    """Proxy for a not-yet-materialized collective result.

    Any interaction — ``__jax_array__`` (every jnp function), arithmetic,
    indexing, or attribute access — forces the owning scope's flush and
    then behaves as the materialized value. ``shape``/``dtype``/``ndim``/
    ``size`` answer WITHOUT forcing (from the recorded payload aval).

    Consumption contract: a deferred result must reach the compiler
    through jnp-level operations (which convert via ``__jax_array__`` at
    user level). Passing one RAW into ``jax.lax.*`` makes jax's
    ``get_aval`` call ``__jax_array__`` during primitive binding, where a
    freshly-traced flush op is an "unexpected tracer" — wrap such
    arguments in ``jnp.asarray`` first (the kmeans|| seeding stage does
    exactly this before ``lax.top_k``)."""

    __slots__ = ("_scope", "_shape", "_dtype", "_value")

    def __init__(self, scope, shape, dtype):
        self._scope = scope
        self._shape = tuple(shape)
        self._dtype = dtype
        self._value = None

    # -- materialization --------------------------------------------------
    def _set(self, value):
        self._value = value

    def _force(self):
        if self._value is None:
            self._scope.flush()
        return self._value

    def __jax_array__(self):
        return self._force()

    # -- aval properties (no force) ---------------------------------------
    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return self._dtype

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def size(self):
        n = 1
        for d in self._shape:
            n *= int(d)
        return n

    # -- everything else forces -------------------------------------------
    def __getattr__(self, name):
        # only reached when normal lookup fails (slots above): delegate
        # to the materialized value (.astype, .sum, .T, .at, ...)
        return getattr(self._force(), name)

    def __getitem__(self, idx):
        return self._force()[idx]

    def __bool__(self):
        # force, then let jax raise its TracerBoolConversionError exactly
        # as the unfused path would — without this, Python's __len__
        # fallback would silently truth-test a scalar as False
        return bool(self._force())

    def __len__(self):
        if not self._shape:
            raise TypeError("len() of a 0-d deferred collective result")
        return self._shape[0]

    def __repr__(self):
        return (f"_Deferred(shape={self._shape}, dtype={self._dtype}, "
                f"materialized={self._value is not None})")

    def __neg__(self):
        return -self._force()

    def __pos__(self):
        return +self._force()

    def __abs__(self):
        return abs(self._force())


def _undefer(v):
    return v._force() if isinstance(v, _Deferred) else v


def _binop(opname, reflected=False):
    import operator
    op = getattr(operator, opname)

    def fwd(self, other):
        a, b = self._force(), _undefer(other)
        return op(b, a) if reflected else op(a, b)
    return fwd


for _name, _sym in [("add", "add"), ("sub", "sub"), ("mul", "mul"),
                    ("truediv", "truediv"), ("floordiv", "floordiv"),
                    ("mod", "mod"), ("pow", "pow"), ("matmul", "matmul"),
                    ("and", "and_"), ("or", "or_"), ("xor", "xor"),
                    ("lt", "lt"), ("le", "le"), ("gt", "gt"), ("ge", "ge"),
                    ("eq", "eq"), ("ne", "ne")]:
    setattr(_Deferred, f"__{_name}__", _binop(_sym))
    if _name not in ("lt", "le", "gt", "ge", "eq", "ne"):
        setattr(_Deferred, f"__r{_name}__", _binop(_sym, reflected=True))
del _name, _sym
# defining __eq__ cleared the default __hash__; proxies are plain unique
# objects (identity hash), never value-compared as dict keys
_Deferred.__hash__ = object.__hash__


# one pending collective: lane-grouped at flush time
class _Pending:
    __slots__ = ("payload", "name", "num_workers", "negate", "kind_label",
                 "raw_op", "deferred", "gather")

    def __init__(self, payload, name, num_workers, negate, kind_label,
                 raw_op, deferred, gather=False):
        self.payload = payload
        self.name = name
        self.num_workers = num_workers
        self.negate = negate
        self.kind_label = kind_label
        self.raw_op = raw_op
        self.deferred = deferred
        self.gather = gather


class _FusionScope:
    """Deferred-reduction accumulator for one superstep trace.

    Lanes are keyed by ``(family, axis_name, lane_op, dtype)``; each lane
    flushes as ONE collective (flattened + offset-sliced when it holds
    more than one payload, the raw op on the original payload when it
    holds exactly one)."""

    def __init__(self):
        self._order: List[tuple] = []
        self._lanes: Dict[tuple, List[_Pending]] = {}
        # (kind, member-names, bytes) of every >1-member flush — test and
        # observability introspection
        self.fused_groups: List[tuple] = []

    # -- registration ------------------------------------------------------
    def _register(self, key, pending):
        if key not in self._lanes:
            self._lanes[key] = []
            self._order.append(key)
        self._lanes[key].append(pending)

    def defer_reduce(self, op: str, x, axis_name, name: str,
                     num_workers: int, kind_label: str = "AllReduce"):
        """Defer a psum/pmax/pmin over a payload pytree; returns the
        matching pytree of :class:`_Deferred` proxies."""
        raw = {"sum": jax.lax.psum, "max": jax.lax.pmax,
               "min": jax.lax.pmin}[op]

        def leaf(v):
            v = jnp.asarray(v)  # forces deferred inputs first (dependency)
            lane_op, negate = op, False
            if op == "min" and jnp.issubdtype(v.dtype, jnp.inexact):
                # min(x) == -max(-x), exact for floats: the min payload
                # rides the max lane so pmax+pmin pairs fuse to one op
                lane_op, negate = "max", True
            d = _Deferred(self, v.shape, v.dtype)
            self._register(("red", axis_name, lane_op, str(v.dtype)),
                           _Pending(v, name, num_workers, negate,
                                    kind_label, raw, d))
            return d
        return jax.tree_util.tree_map(leaf, x)

    def defer_gather(self, x, axis_name, axis: int, tiled: bool,
                     name: str, num_workers: int):
        """Defer an all_gather (axis-0, untiled form only — the fusable
        layout); other forms lower eagerly with a manifest record."""
        if axis != 0 or tiled:
            record_collective("AllGather", name, payload_nbytes(x),
                              num_workers)
            return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)

        def leaf(v):
            v = jnp.asarray(v)
            d = _Deferred(self, (num_workers,) + tuple(v.shape), v.dtype)
            self._register(("gather", axis_name, str(v.dtype)),
                           _Pending(v, name, num_workers, False,
                                    "AllGather", None, d, gather=True))
            return d
        return jax.tree_util.tree_map(leaf, x)

    # -- flush -------------------------------------------------------------
    def flush(self):
        """Materialize every pending collective: one lax op per lane."""
        if not self._order:
            return
        order, lanes = self._order, self._lanes
        self._order, self._lanes = [], {}
        for key in order:
            entries = lanes[key]
            if len(entries) == 1:
                e = entries[0]
                # single payload: the raw op on the ORIGINAL payload —
                # byte-identical lowering to the unfused wrapper
                record_collective(e.kind_label, e.name,
                                  payload_nbytes(e.payload), e.num_workers)
                if e.gather:
                    e.deferred._set(jax.lax.all_gather(e.payload, key[1]))
                else:
                    e.deferred._set(e.raw_op(e.payload, key[1]))
                continue
            axis_name = key[1]
            flats = [(-jnp.ravel(e.payload) if e.negate
                      else jnp.ravel(e.payload)) for e in entries]
            sizes = [f.size for f in flats]
            buf = jnp.concatenate(flats)
            names = tuple(e.name for e in entries)
            per_worker = sum(payload_nbytes(e.payload) for e in entries)
            # keep the members' kind label when they agree (a pure
            # ctx.all_reduce_sum group stays "InlineAllReduce" fused or
            # not); mixed groups fall back to the generic kind
            kinds = {e.kind_label for e in entries}
            if len(kinds) == 1:
                kind = entries[0].kind_label
            else:
                kind = "AllGather" if key[0] == "gather" else "AllReduce"
            record_collective(kind, "fused(" + "+".join(names) + ")",
                              per_worker, entries[0].num_workers,
                              members=names)
            self.fused_groups.append((kind, names, per_worker))
            if key[0] == "gather":
                out = jax.lax.all_gather(buf, axis_name)   # (nw, total)
                off = 0
                for e, sz in zip(entries, sizes):
                    piece = out[:, off:off + sz]
                    e.deferred._set(piece.reshape(
                        (out.shape[0],) + tuple(e.payload.shape)))
                    off += sz
            else:
                lane_op = key[2]
                raw = {"sum": jax.lax.psum, "max": jax.lax.pmax,
                       "min": jax.lax.pmin}[lane_op]
                out = raw(buf, axis_name)
                off = 0
                for e, sz in zip(entries, sizes):
                    piece = out[off:off + sz]
                    if e.negate:
                        piece = -piece
                    e.deferred._set(piece.reshape(e.payload.shape))
                    off += sz


@contextlib.contextmanager
def fusing(enabled: bool = True):
    """Install a :class:`_FusionScope` on this thread (the engine arms one
    per superstep trace). Pending collectives flush on first use and, as
    a backstop, when the scope exits cleanly."""
    if not enabled:
        yield None
        return
    prev = getattr(_fusion, "scope", None)
    scope = _FusionScope()
    _fusion.scope = scope
    try:
        yield scope
        scope.flush()
    finally:
        _fusion.scope = prev


def resolve_deferred(tree):
    """Replace every :class:`_Deferred` leaf with its materialized value
    (the engine runs this over the carry before it leaves the superstep —
    deferred proxies must never reach ``lax.while_loop``)."""
    return jax.tree_util.tree_map(
        lambda v: v._force() if isinstance(v, _Deferred) else v, tree,
        is_leaf=lambda v: isinstance(v, _Deferred))


# -- manifest-recording raw-collective wrappers -----------------------------
# The collective manifest only saw traffic routed through the stage
# classes above (and ctx.all_reduce_sum); raw ``lax.psum``/... calls in
# operator code ran real inter-chip traffic the accounting, the scaling
# evidence, and the planned ROADMAP-item-1 psum fusion could not see.
# These wrappers are the sanctioned call form outside this module — the
# alink-lint COLLECTIVE-SITE rule rejects raw ``lax`` collectives
# anywhere else. Each wrapper records at TRACE time (once per traced
# call site — a site inside a scan body records once per trace, and the
# engine multiplies per-superstep manifests by the executed superstep
# count; loops that drive jit-cached programs outside the engine replay
# the captured manifest per invocation via record_manifest) and lowers
# to exactly the raw ``lax`` op: zero HLO change. Inside an armed
# ``fusing()`` scope they DEFER instead (see the fusion block above).

def manifest_psum(x, axis_name, *, name: str = "<psum>",
                  num_workers: int = 1):
    """``lax.psum`` + manifest record (kind AllReduce)."""
    scope = active_fusion_scope()
    if scope is not None:
        return scope.defer_reduce("sum", x, axis_name, name, num_workers)
    record_collective("AllReduce", name, payload_nbytes(x), num_workers)
    return jax.lax.psum(x, axis_name)


def manifest_pmax(x, axis_name, *, name: str = "<pmax>",
                  num_workers: int = 1):
    """``lax.pmax`` + manifest record (kind AllReduce)."""
    scope = active_fusion_scope()
    if scope is not None:
        return scope.defer_reduce("max", x, axis_name, name, num_workers)
    record_collective("AllReduce", name, payload_nbytes(x), num_workers)
    return jax.lax.pmax(x, axis_name)


def manifest_pmin(x, axis_name, *, name: str = "<pmin>",
                  num_workers: int = 1):
    """``lax.pmin`` + manifest record (kind AllReduce)."""
    scope = active_fusion_scope()
    if scope is not None:
        return scope.defer_reduce("min", x, axis_name, name, num_workers)
    record_collective("AllReduce", name, payload_nbytes(x), num_workers)
    return jax.lax.pmin(x, axis_name)


def manifest_all_gather(x, axis_name, *, axis: int = 0, tiled: bool = False,
                        name: str = "<all_gather>", num_workers: int = 1):
    """``lax.all_gather`` + manifest record (kind AllGather; bytes are
    the pre-gather shard payload × workers, like the AllGather stage)."""
    scope = active_fusion_scope()
    if scope is not None:
        return scope.defer_gather(x, axis_name, axis, tiled, name,
                                  num_workers)
    record_collective("AllGather", name, payload_nbytes(x), num_workers)
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def manifest_psum_scatter(x, axis_name, *, scatter_dimension: int = 0,
                          tiled: bool = False,
                          name: str = "<psum_scatter>",
                          num_workers: int = 1):
    """``lax.psum_scatter`` + manifest record (kind ReduceScatter)."""
    record_collective("ReduceScatter", name, payload_nbytes(x), num_workers)
    return jax.lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=tiled)


class CommunicateFunction:
    """Marker base (reference comqueue/CommunicateFunction.java)."""

    def calc(self, context: ComContext):  # pragma: no cover - interface
        raise NotImplementedError


class AllReduce(CommunicateFunction):
    """All-reduce named carry buffers across workers.

    reference: communication/AllReduce.java:85-120 (SUM/MAX/MIN ops :125-159).
    ``lax.psum`` rides the ICI; the reference's TRANSFER_BUFFER_SIZE=4096
    chunking machinery has no analogue here.
    """

    OPS = {"sum": jax.lax.psum, "max": jax.lax.pmax, "min": jax.lax.pmin}

    def __init__(self, *buffer_names: str, op: str = "sum",
                 mean: bool = False):
        if not buffer_names:
            raise ValueError("AllReduce needs at least one buffer name")
        self.buffer_names = buffer_names
        if op.lower() not in self.OPS:
            raise ValueError(f"unsupported allreduce op {op}; use sum/max/min")
        self.op = op.lower()
        if mean and self.op != "sum":
            raise ValueError("mean=True only makes sense with op='sum'")
        self.mean = mean

    def calc(self, context: ComContext):
        wrap = {"sum": manifest_psum, "max": manifest_pmax,
                "min": manifest_pmin}[self.op]
        for name in self.buffer_names:
            v = context.get_obj(name)
            # route through the manifest wrapper: eagerly it records +
            # lowers the identical raw op; inside the engine's fusing()
            # scope the reduction DEFERS, so adjacent AllReduce stages
            # (Newton's H + glw, FM's avg + lw) coalesce into one psum
            out = wrap(v, ComContext.AXIS, name=name,
                       num_workers=context.num_task)
            if self.mean:
                # dividing forces a deferred result — mean reductions
                # materialize eagerly (word2vec's one psum loses nothing)
                out = jax.tree_util.tree_map(
                    lambda x: x / context.num_task, out,
                    is_leaf=lambda x: isinstance(x, _Deferred))
            context.put_obj(name, out)


class AllGather(CommunicateFunction):
    """Gather per-worker arrays into a replicated stacked array.

    The ALS "factor all-gather" primitive (SURVEY §2.3 block parallelism);
    result shape: (num_workers, *shard_shape), stored under
    ``<name><suffix>``.
    """

    def __init__(self, *buffer_names: str, suffix: str = "_gathered", axis: int = 0,
                 tiled: bool = False):
        self.buffer_names = buffer_names
        self.suffix = suffix
        self.axis = axis
        self.tiled = tiled

    def calc(self, context: ComContext):
        for name in self.buffer_names:
            v = context.get_obj(name)
            # manifest wrapper: identical eager lowering; defers (and can
            # fuse adjacent gathers) inside the engine's fusing() scope
            out = manifest_all_gather(v, ComContext.AXIS, axis=self.axis,
                                      tiled=self.tiled, name=name,
                                      num_workers=context.num_task)
            context.put_obj(name + self.suffix, out)


class BroadcastFromWorker0(CommunicateFunction):
    """Replicate worker 0's value of a buffer to all workers.

    reference: the node-0 criterion rebroadcast pattern (BaseComQueue.java:242-304).
    """

    def __init__(self, *buffer_names: str):
        self.buffer_names = buffer_names

    def calc(self, context: ComContext):
        tid = context.task_id
        for name in self.buffer_names:
            v = context.get_obj(name)
            record_collective("BroadcastFromWorker0", name, payload_nbytes(v),
                              context.num_task)

            def bcast(x):
                x = jnp.where(tid == 0, x, jnp.zeros_like(x))
                return jax.lax.psum(x, ComContext.AXIS)

            context.put_obj(name, jax.tree_util.tree_map(bcast, v))


def distributed_info_start(total, task_id, num_tasks):
    """Start offset of ``task_id``'s slice of ``total`` items.

    reference: DefaultDistributedInfo.startPos (io/directreader/) — first
    ``total % n`` workers get one extra item. Traceable arithmetic.
    """
    total = jnp.asarray(total)
    base = total // num_tasks
    rem = total % num_tasks
    return task_id * base + jnp.minimum(task_id, rem)


def distributed_info_count(total, task_id, num_tasks):
    """Length of ``task_id``'s slice (DefaultDistributedInfo.localRowCnt)."""
    total = jnp.asarray(total)
    base = total // num_tasks
    rem = total % num_tasks
    return base + (task_id < rem).astype(total.dtype)
