"""ENV-KEY-FOLD negative: every env read reachable from the factory is
either declared to fold into this factory's key dimension
(ALINK_TPU_GOOD -> program_cache) or declared key-neutral
(ALINK_TPU_NEUTRAL); constant-name indirection must resolve."""
import os

GOOD_ENV = "ALINK_TPU_GOOD"


def make_program(stages):
    folded = os.environ.get(GOOD_ENV)               # via module constant
    neutral = os.environ.get("ALINK_TPU_NEUTRAL")   # key-neutral
    return (stages, folded, neutral)
