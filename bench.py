"""Benchmark: LogisticRegression training throughput (north-star workload).

Measures samples/sec/chip training a Criteo-style sparse CTR
LogisticRegression (32 hashed fields x 2048, dim=65536 — the FTRLExample /
ftrl_demo config shape) with the distributed L-BFGS BSP program.
Features use field-aware hashing (one field per raw column — the
field-blocked format, ops/fieldblock.py) so the sparse gradient runs on
the MXU via factored one-hots instead of XLA's serialized random
gather/scatter.

The reference publishes no numbers (BASELINE.md), so ``vs_baseline``
compares against a numpy/BLAS implementation of the same superstep on the
host CPU — the stand-in for one Flink task-slot worker.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np

N_FIELDS, FIELD_SIZE = 32, 2048
DIM = N_FIELDS * FIELD_SIZE


def make_data(n_rows: int, seed: int = 0):
    """Field-aware-hashed CTR data: one local index per field per sample."""
    rng = np.random.RandomState(seed)
    fb_idx = rng.randint(0, FIELD_SIZE, size=(n_rows, N_FIELDS)).astype(np.int32)
    w_true = (rng.randn(DIM) * (rng.rand(DIM) < 0.05)).astype(np.float32)
    flat = fb_idx + (np.arange(N_FIELDS, dtype=np.int32) * FIELD_SIZE)[None, :]
    margin = w_true[flat].sum(-1)
    y = np.where(rng.rand(n_rows) < 1.0 / (1.0 + np.exp(-margin)), 1.0, -1.0
                 ).astype(np.float32)
    return fb_idx, y


def tpu_run(fb_idx, y, iters: int):
    """Wall-seconds for `iters` L-BFGS supersteps (compile excluded).

    Both programs (1-iter and 1+iters) are compiled once into JAX's
    persistent compilation cache during warmup; the measured runs then
    pay only retrace + cache lookup + execution, so the delta isolates
    the superstep cost."""
    import tempfile

    import jax
    jax.config.update("jax_compilation_cache_dir", tempfile.mkdtemp())
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    from alink_tpu.common.mlenv import MLEnvironment, MLEnvironmentFactory
    from alink_tpu.operator.common.optim.objfunc import (LogLossFunc,
                                                         UnaryLossObjFunc)
    from alink_tpu.operator.common.optim.optimizers import OptimParams, optimize
    from alink_tpu.ops.fieldblock import FieldBlockMeta

    env = MLEnvironment()
    MLEnvironmentFactory.set_default(env)
    meta = FieldBlockMeta(N_FIELDS, FIELD_SIZE)
    data = {"fb_idx": fb_idx, "y": y, "w": np.ones(len(y), np.float32)}

    wrng = np.random.RandomState(123)

    def run(n_iter):
        obj = UnaryLossObjFunc(LogLossFunc(), DIM, l2=1e-4, fb_meta=meta)
        # distinct tiny warm start per call: defeats any execution-result
        # memoization between identical (program, inputs) pairs in the
        # runtime, so every timed call does real device work
        w0 = (wrng.randn(DIM) * 1e-6).astype(np.float32)
        t0 = time.perf_counter()
        optimize(obj, data, OptimParams(method="LBFGS", max_iter=n_iter,
                                        epsilon=0.0), env, warm_start=w0)
        return time.perf_counter() - t0

    run(1)                   # compile 1-iter program into the cache
    run(1 + iters)           # compile loop program into the cache
    # median-of-3 per program: per-call overhead (retrace + tunnel
    # transfer) is noisy at the ~0.5 s level; the long measured span
    # (iters supersteps) keeps the delta well above that noise floor
    t1 = sorted(run(1) for _ in range(3))[1]
    t_full = sorted(run(1 + iters) for _ in range(3))[1]
    return max(t_full - t1, 1e-9), env.num_workers


def cpu_baseline(fb_idx, y, iters: int) -> float:
    """Same superstep in numpy (gather, scatter-add grad, 11-point line search)."""
    n = len(y)
    flat = fb_idx + (np.arange(N_FIELDS, dtype=np.int32) * FIELD_SIZE)[None, :]
    coef = np.zeros(DIM, np.float32)
    w = np.ones(n, np.float32)
    steps = np.concatenate([[0.0], 2.0 ** (1 - np.arange(10))]).astype(np.float32)
    t0 = time.perf_counter()
    for _ in range(iters):
        eta = coef[flat].sum(-1)
        c = w * (-y / (1.0 + np.exp(y * eta)))
        g = np.zeros(DIM, np.float32)
        np.add.at(g, flat.reshape(-1), np.repeat(c, N_FIELDS))
        d = g
        eta_d = d[flat].sum(-1)
        losses = []
        for s in steps:
            m = y * (eta - s * eta_d)
            losses.append((w * np.logaddexp(0.0, -m)).sum())
        coef = coef - steps[int(np.argmin(losses))] * d
    return time.perf_counter() - t0


def main():
    n_rows, iters = 200_000, 300
    fb_idx, y = make_data(n_rows)
    tpu_t, n_chips = tpu_run(fb_idx, y, iters)
    tpu_sps = n_rows * iters / tpu_t / max(n_chips, 1)

    base_iters = 3
    cpu_t = cpu_baseline(fb_idx, y, base_iters)
    cpu_sps = n_rows * base_iters / cpu_t

    print(json.dumps({
        "metric": "logreg_criteo_lbfgs_samples_per_sec_per_chip",
        "value": round(tpu_sps, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round(tpu_sps / cpu_sps, 3),
    }))


if __name__ == "__main__":
    main()
