"""KMeans batch operators + model.

Re-design of batch/clustering/KMeansTrainBatchOp.java:60-120 and
KMeansPredictBatchOp / common/clustering/kmeans/KMeansModelDataConverter.
"""

from __future__ import annotations

import json
from typing import List, Optional

import numpy as np

from ....common.mtable import MTable
from ....common.params import ParamInfo, Params, RangeValidator, InValidator
from ....common.types import AlinkTypes, TableSchema
from ....mapper.base import ModelMapper, OutputColsHelper
from ....model.converters import SimpleModelDataConverter, decode_array, encode_array
from ....params.shared import (HasFeatureCols, HasMaxIterDefaultAs50,
                               HasPredictionCol, HasReservedCols, HasSeed,
                               HasVectorCol)
from ...base import BatchOperator
from ...common.clustering.kmeans import assign_clusters, kmeans_train
from ...common.dataproc.feature_extract import extract_design, resolve_feature_cols
from ..utils.model_map import ModelMapBatchOp


class KMeansModelData:
    def __init__(self, centroids: np.ndarray, weights: np.ndarray,
                 distance_type: str, vector_col: Optional[str],
                 feature_cols: Optional[List[str]]):
        self.centroids = centroids
        self.weights = weights
        self.distance_type = distance_type
        self.vector_col = vector_col
        self.feature_cols = feature_cols

    @property
    def k(self):
        return self.centroids.shape[0]


class KMeansModelDataConverter(SimpleModelDataConverter):
    """reference: common/clustering/kmeans/KMeansModelDataConverter.java"""

    def serialize_model(self, m: KMeansModelData):
        meta = Params({"k": int(m.k), "distance_type": m.distance_type,
                       "vector_col": m.vector_col, "feature_cols": m.feature_cols})
        return meta, [encode_array(m.centroids), encode_array(m.weights)]

    def deserialize_model(self, meta: Params, data):
        return KMeansModelData(
            centroids=decode_array(data[0]), weights=decode_array(data[1]),
            distance_type=meta._m.get("distance_type", "EUCLIDEAN"),
            vector_col=meta._m.get("vector_col"),
            feature_cols=meta._m.get("feature_cols"))


class _KMeansParams(HasVectorCol, HasFeatureCols, HasMaxIterDefaultAs50, HasSeed):
    K = ParamInfo("k", int, "number of clusters", default=2,
                  validator=RangeValidator(1, None))
    EPSILON = ParamInfo("epsilon", float, "centroid-movement tolerance", default=1e-4)
    DISTANCE_TYPE = ParamInfo("distance_type", str, default="EUCLIDEAN",
                              validator=InValidator(["EUCLIDEAN", "COSINE"]))
    INIT_MODE = ParamInfo("init_mode", str, default="K_MEANS_PARALLEL",
                          validator=InValidator(["RANDOM", "K_MEANS_PARALLEL"]))


class KMeansTrainBatchOp(BatchOperator, _KMeansParams):
    def link_from(self, in_op: BatchOperator) -> "KMeansTrainBatchOp":
        t = in_op.get_output_table()
        vector_col = self.params._m.get("vector_col")
        feature_cols = self.params._m.get("feature_cols")
        if not vector_col:
            feature_cols = resolve_feature_cols(t, feature_cols)
        import jax
        dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
        design = extract_design(t, feature_cols, vector_col, dtype)
        X = design["X"] if design["kind"] == "dense" else None
        if X is None:
            from ....common.vector import SparseBatch
            X = SparseBatch(design["idx"], design["val"], design["dim"]).to_dense(dtype)
        cents, wts, steps = kmeans_train(
            X, k=self.get_k(), max_iter=self.get_max_iter(),
            tol=self.get_epsilon(), distance_type=self.get_distance_type(),
            init=self.get_init_mode(), seed=self.get_seed())
        model = KMeansModelData(np.asarray(cents, np.float64),
                                np.asarray(wts, np.float64),
                                self.get_distance_type(), vector_col, feature_cols)
        self._output = KMeansModelDataConverter().save_model(model)
        self._side_outputs = [MTable({"cluster_id": np.arange(model.k),
                                      "weight": model.weights})]
        self._steps = steps
        return self


class KMeansModelMapper(ModelMapper):
    """reference: common/clustering/kmeans/KMeansModelMapper.java"""

    def __init__(self, model_schema, data_schema, params=None, **kwargs):
        super().__init__(model_schema, data_schema, params, **kwargs)
        self.model: Optional[KMeansModelData] = None

    def load_model(self, model_table: MTable):
        self.model = KMeansModelDataConverter().load_model(model_table)

    def get_output_schema(self) -> TableSchema:
        pred_col = self.params._m.get("prediction_col", "cluster_id")
        dist_col = self.params._m.get("prediction_distance_col")
        reserved = self.params._m.get("reserved_cols")
        cols, types = [pred_col], [AlinkTypes.LONG]
        if dist_col:
            cols.append(dist_col)
            types.append(AlinkTypes.DOUBLE)
        return OutputColsHelper(self.data_schema, cols, types, reserved).get_output_schema()

    def map_table(self, data: MTable) -> MTable:
        m = self.model
        design = extract_design(data, m.feature_cols, m.vector_col, np.float64)
        X = design["X"] if design["kind"] == "dense" else None
        if X is None:
            from ....common.vector import SparseBatch
            X = SparseBatch(design["idx"], design["val"], design["dim"]).to_dense(np.float64)
        ids, dists = assign_clusters(X, m.centroids, m.distance_type)
        ids = np.asarray(ids, np.int64)
        dists = np.sqrt(np.maximum(np.asarray(dists, np.float64), 0.0)) \
            if m.distance_type == "EUCLIDEAN" else np.asarray(dists, np.float64)
        pred_col = self.params._m.get("prediction_col", "cluster_id")
        dist_col = self.params._m.get("prediction_distance_col")
        reserved = self.params._m.get("reserved_cols")
        cols, types, vals = [pred_col], [AlinkTypes.LONG], [ids]
        if dist_col:
            cols.append(dist_col)
            types.append(AlinkTypes.DOUBLE)
            vals.append(dists)
        return OutputColsHelper(data.schema, cols, types, reserved).build_output(data, vals)


class KMeansPredictBatchOp(ModelMapBatchOp, HasPredictionCol, HasReservedCols):
    MAPPER_CLS = KMeansModelMapper
    PREDICTION_DISTANCE_COL = ParamInfo("prediction_distance_col", str,
                                        "output distance column")
