#!/usr/bin/env python
"""Tuning-sweep smoke (perf_gate leg, ISSUE 12) — exit 6 on failure.

A small grid runs through BOTH paths:

  * serial — N full ``optimize()`` execs (the reference-shaped
    candidate loop);
  * sweep  — ONE compiled BSP program over the ``(points,)`` lane,
    full-depth for the parity checks, plus an ASHA run for the
    early-stopping checks.

Asserted (the load-bearing sweep contracts, cheap enough for every
gate run):
  1. per-point BITWISE parity: every full-sweep point equals its serial
     fit (coef + executed step count);
  2. best-point identity: the full sweep's argmin-loss winner is the
     serial grid's winner, and the ASHA run keeps that same winner with
     a bitwise-equal model;
  3. determinism: two ASHA runs produce identical survivors and rungs;
  4. compile-group invariant: ONE compiled program serves the whole
     carry-resident grid (engine cache misses == 1 for the first run,
     0 for the repeat);
  5. speedup sanity: the ASHA sweep is not slower than the serial loop
     (the real >=5x claim is the bench row's; the gate only catches a
     sweep that fell back to serial economics).
"""

import os
import subprocess
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

EXIT = 6
_MARK = "ALINK_SWEEP_SMOKE_CHILD"


def main() -> int:
    if os.environ.get(_MARK) != "1":
        # re-exec in a fresh interpreter on a 4-virtual-device f64 mesh
        # (bootenv.cpu_mesh_env — XLA device-count flags latch at
        # backend init, so the parent process cannot widen its own mesh)
        import bootenv
        env = bootenv.cpu_mesh_env(4)
        env[_MARK] = "1"
        env["JAX_ENABLE_X64"] = "1"
        out = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             cwd=ROOT, env=env, timeout=900)
        return out.returncode
    from alink_tpu.common.mlenv import MLEnvironmentFactory
    from alink_tpu.engine.comqueue import program_cache_stats
    from alink_tpu.operator.common.optim.objfunc import (LogLossFunc,
                                                         UnaryLossObjFunc)
    from alink_tpu.operator.common.optim.optimizers import (OptimParams,
                                                            optimize)
    from alink_tpu.tuning import AshaConfig, sweep_optimize

    env = MLEnvironmentFactory.get_default()
    rng = np.random.RandomState(0)
    n, d, iters = 2000, 16, 24
    X = rng.randn(n, d)
    y = np.sign(X @ rng.randn(d) + 0.3 * rng.randn(n))
    data = {"X": X, "y": y, "w": np.ones(n)}
    obj = UnaryLossObjFunc(LogLossFunc(), d)
    base = OptimParams(method="LBFGS", max_iter=iters, epsilon=0.0)
    l2s = [0.0] + [float(1e-3 * (2.2 ** i)) for i in range(8)]
    pts = [{"l2": l2} for l2 in l2s]
    asha = AshaConfig(rung=3, eta=3)
    bad = []

    serial = []
    t0 = time.perf_counter()
    for pt in pts:
        o = UnaryLossObjFunc(LogLossFunc(), d, l2=pt["l2"])
        coef, curve, steps = optimize(o, data, OptimParams(
            method="LBFGS", max_iter=iters, epsilon=0.0), env)
        serial.append((np.asarray(coef), np.asarray(curve), int(steps)))
    t_serial_cold = time.perf_counter() - t0

    miss0 = program_cache_stats()["misses"]
    full = sweep_optimize(obj, data, base, pts, env=env)
    miss1 = program_cache_stats()["misses"]
    sweep_optimize(obj, data, base, pts, env=env)
    miss2 = program_cache_stats()["misses"]

    # 1. per-point bitwise parity
    for i in range(len(pts)):
        if not np.array_equal(serial[i][0], full.values["coef"][i]):
            bad.append(f"point {i} (l2={pts[i]['l2']}): sweep coef != "
                       f"serial fit (bitwise)")
        if serial[i][2] != int(full.steps[i]):
            bad.append(f"point {i}: step count {int(full.steps[i])} != "
                       f"serial {serial[i][2]}")
    # 2. best-point identity (full + ASHA)
    serial_best = int(np.argmin([c[-1] for _, c, _ in serial]))
    if full.best != serial_best:
        bad.append(f"full-sweep winner {full.best} != serial winner "
                   f"{serial_best}")
    r1 = sweep_optimize(obj, data, base, pts, env=env, asha=asha)
    if r1.best != serial_best:
        bad.append(f"ASHA winner {r1.best} != serial winner {serial_best}")
    elif not np.array_equal(serial[r1.best][0],
                            r1.values["coef"][r1.best]):
        bad.append("ASHA winning model is not bitwise-equal to its "
                   "serial fit")
    # 3. determinism
    r2 = sweep_optimize(obj, data, base, pts, env=env, asha=asha)
    if r1.survivors() != r2.survivors() or r1.rungs != r2.rungs:
        bad.append(f"ASHA not deterministic: survivors "
                   f"{r1.survivors()} vs {r2.survivors()}")
    # 4. one compiled program per compile group
    if miss1 - miss0 != 1:
        bad.append(f"full sweep compiled {miss1 - miss0} programs for "
                   f"one carry-resident group (want 1)")
    if miss2 - miss1 != 0:
        bad.append(f"repeat sweep missed the program cache "
                   f"({miss2 - miss1} new compiles)")
    # 5. speedup sanity (warm serial vs warm ASHA sweep)
    t0 = time.perf_counter()
    for pt in pts:
        o = UnaryLossObjFunc(LogLossFunc(), d, l2=pt["l2"])
        coef, _c, _s = optimize(o, data, OptimParams(
            method="LBFGS", max_iter=iters, epsilon=0.0), env)
        np.asarray(coef)
    t_serial = time.perf_counter() - t0
    t0 = time.perf_counter()
    sweep_optimize(obj, data, base, pts, env=env, asha=asha)
    t_sweep = time.perf_counter() - t0
    speedup = t_serial / max(t_sweep, 1e-9)
    if speedup < 1.0:
        bad.append(f"ASHA sweep SLOWER than the serial loop "
                   f"({speedup:.2f}x) — serial economics")

    if bad:
        print("sweep_smoke: FAILED:", file=sys.stderr)
        for b in bad:
            print(f"  {b}", file=sys.stderr)
        return EXIT
    print(f"sweep_smoke: ok — {len(pts)} points bitwise vs serial, "
          f"winner {serial_best} identical (full + ASHA), deterministic "
          f"rungs {[(r['step'], r['alive_after']) for r in r1.rungs]}, "
          f"1 compiled program, ASHA {speedup:.2f}x the serial loop "
          f"(cold serial leg paid {t_serial_cold:.1f}s for "
          f"{len(pts)} per-candidate compiles the sweep never pays)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
