"""alink_tpu.serving — the compiled low-latency serving tier.

The reference serves predictions through its Mapper/ModelMapper L6/L7
layer (``LocalPredictor``, hot model-stream reload via
``ModelMapperAdapter.loadModel`` — PAPER.md layer map). This package is
that layer rebuilt TPU-first:

* :class:`CompiledPredictor` — lowers a ModelMapper's scoring function
  into per-model jitted programs keyed on (model signature, shape
  bucket); requests pad to the smallest covering bucket so a handful of
  compiled programs serve arbitrary request sizes, and padding rows are
  proven numerical no-ops.
* :class:`PredictServer` — the request micro-batcher: concurrent
  single-row requests coalesce into bucket-sized device batches under a
  latency budget, with admission control/backpressure on the
  stop-aware condition-variable channel from ``operator/stream/
  prefetch.py``.
* hot model swap — :meth:`CompiledPredictor.swap_model` loads new
  weights into the standby model slot (``device_put`` off the serving
  loop) and atomically flips it active between dispatches; a
  :class:`ModelStreamFeeder` taps a model-snapshot stream (the FTRL
  trainer's output) and swaps per snapshot.
* :class:`LoadGenerator` — the closed-loop load generator behind the
  ``bench.py serve_*`` rows (QPS/chip, p50/p99, bucket-hit rate,
  batch occupancy).
* resilience (``resilience.py``) — end-to-end request deadlines with
  typed load shedding (``submit(row, deadline_s=)`` →
  :class:`DeadlineExceeded` BEFORE the dispatch is paid), a
  per-model-version :class:`CircuitBreaker` that degrades compiled-path
  failures to the host-mapper fallback and re-probes on a
  deterministic backoff schedule, supervised feeders (bounded retry /
  poisoned-snapshot skip / last-good-model guarantee) and supervised
  serving loops (crash → typed quarantine + respawn); chaos-tested by
  ``tools/chaos_smoke.py`` + the ``serve_chaos`` bench row.
* multi-chip serving (``sharded.py``) — ``ALINK_TPU_SERVE_SHARDED``
  compiles the bucket programs under the session mesh's partition
  rules (feature-sharded model state placed by ``io/sharding.py``,
  one manifest psum per dispatch, bitwise-identical answers at every
  mesh size); ``ALINK_TPU_SERVE_REPLICAS`` fans ``PredictServer``
  batches across the chips as independent single-device replicas.
* multi-tenant fleet (``fleet.py``) — :class:`ModelRegistry` groups
  tenants by serving-kernel geometry (one :class:`ServingPlan` per
  group) so same-geometry models share compiled bucket programs;
  :class:`FleetServer` routes per-request tenant ids, coalesces
  cross-tenant batches through lane-stacked programs (bitwise no-op
  vs per-tenant dispatch), LRU-evicts cold tenants' device weights
  under ``ALINK_TPU_FLEET_HBM_BUDGET`` with snapshot-store
  re-admission, and isolates tenants with quotas + per-tenant
  breakers; one ``ModelStreamFeeder`` multiplexes per-tenant swap
  streams via :meth:`FleetServer.feeder_target`.

See docs/serving.md for the bucket/padding contract, swap atomicity,
admission control, and load-generator usage.
"""

from .plan import ServingPlan
from .predictor import (CompiledPredictor, ServingKernel,
                        serve_buckets, serve_compiled_enabled)
from .server import (DeviceWeightsFeeder, ModelStreamFeeder, PredictServer,
                     RequestFuture)
from .fleet import (FleetServer, ModelRegistry, fleet_coalesce_enabled,
                    fleet_hbm_budget, fleet_lanes, fleet_tenant_quota)
from .loadgen import LoadGenerator, LoadReport, percentile, serial_qps
from .resilience import (CircuitBreaker, DeadlineExceeded, ReplicaCrashed,
                         RequestCancelled, TenantQuotaExceeded,
                         serve_breaker_enabled)
from .sharded import serve_replicas, serve_sharded_enabled, serving_mesh

__all__ = [
    "CompiledPredictor", "ServingKernel", "ServingPlan", "PredictServer",
    "RequestFuture", "ModelStreamFeeder", "DeviceWeightsFeeder",
    "FleetServer", "ModelRegistry", "LoadGenerator",
    "LoadReport", "percentile", "serial_qps", "serve_buckets",
    "serve_compiled_enabled", "serve_replicas", "serve_sharded_enabled",
    "serving_mesh", "CircuitBreaker", "DeadlineExceeded", "ReplicaCrashed",
    "RequestCancelled", "TenantQuotaExceeded", "serve_breaker_enabled",
    "fleet_coalesce_enabled", "fleet_hbm_budget", "fleet_lanes",
    "fleet_tenant_quota",
]
