"""PredictServer — request micro-batching over the compiled predictor.

The reference's serving story is per-row (``LocalPredictor.map``); at
"millions of users" scale per-row device dispatch burns the chip on
launch latency. The micro-batcher coalesces concurrent single-row
requests into bucket-sized device batches under a latency budget:

* requests enter through the stop-aware condition-variable channel from
  ``operator/stream/prefetch.py`` (``_Channel``) — the bound IS the
  admission control: a full queue blocks submitters (backpressure)
  instead of growing latency unboundedly;
* ONE serving-loop thread drains the channel: the first request of a
  batch opens a ``ALINK_TPU_SERVE_WINDOW_MS`` window; the batch
  dispatches when it reaches the top bucket size or the window closes,
  whichever is first. A queue that already holds a full batch never
  waits (the timed ``get(timeout=0)`` fast path);
* each batch runs through :class:`~alink_tpu.serving.predictor.
  CompiledPredictor` — one encode, one compiled program execution, one
  fetch — and the per-request results fan back out through per-request
  futures;
* hot model swap: :meth:`PredictServer.swap_model` delegates to the
  predictor's double-buffered slot flip ON THE CALLER'S THREAD; the
  serving loop picks the new model up at its next dispatch without ever
  blocking. :class:`ModelStreamFeeder` taps a model-snapshot stream
  (e.g. ``FtrlTrainStreamOp``'s output — reference hot model-stream
  reload, ``ModelMapperAdapter.loadModel``) and swaps per snapshot.

Observability: ``serve.request``/``serve.batch``/``serve.swap`` tracer
spans, and ``alink_serve_{requests_total,batch_occupancy,queue_depth,
p99_seconds,model_swaps_total}`` metrics (docs/observability.md).
"""

from __future__ import annotations

import threading
import time
import warnings
from collections import deque
from typing import Callable, List, Optional, Tuple

from ..common import reqtrace
from ..common.adminz import acquire_admin, release_admin
from ..common.faults import FaultInjected
from ..common.metrics import get_registry, metrics_enabled
from ..common.mtable import MTable
from ..common.tracing import trace_complete, trace_instant
from ..operator.stream.prefetch import _Channel, _EMPTY, _SENTINEL
from .loadgen import percentile as _percentile
from .predictor import (CompiledPredictor, serve_min_fill,
                        serve_queue_depth, serve_window_s)
from .resilience import (OPEN, CircuitBreaker, DeadlineExceeded,
                         ReplicaCrashed, RequestCancelled,
                         classify_feeder_error, feeder_backoff_s,
                         feeder_retries, record_feeder_error,
                         record_shed, serve_breaker_enabled)

_P99_RING = 4096        # rolling latency window behind the p99 gauge
_P99_EVERY = 128        # gauge refresh cadence (requests)


class RequestFuture:
    """One in-flight request: the submitter blocks on :meth:`result`;
    the serving loop delivers via :meth:`set_result`/``set_exception``.
    Latency (submit -> delivery) is recorded as the ``serve.request``
    span when the result lands.

    **Cancellation / deadline semantics (ISSUE 14).** A ``result(
    timeout=)`` that raises ``TimeoutError`` does NOT remove the request
    — it stays live in the queue, is still dispatched, and its answer
    lands in this future (the submitter just stopped waiting). To bound
    the *server's* work, not merely the caller's patience, either pass
    ``deadline_s=`` to ``submit()`` (the serving loop sheds the request
    with a typed :class:`~alink_tpu.serving.resilience.DeadlineExceeded`
    BEFORE paying the dispatch once its queue wait exceeds the budget)
    or call :meth:`cancel` (best-effort: the loop sheds a cancelled
    request it has not dispatched yet with :class:`~alink_tpu.serving.
    resilience.RequestCancelled`)."""

    __slots__ = ("row", "_event", "_value", "_error", "submitted_at",
                 "deadline_s", "_cancelled", "ctx")

    def __init__(self, row: Tuple, deadline_s: Optional[float] = None):
        self.row = row
        self._event = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None
        self.submitted_at = time.perf_counter()
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self._cancelled = False
        # request-scoped timeline (ISSUE 18) — None while the layer is
        # off; every consumer tolerates that
        self.ctx: Optional[reqtrace.RequestContext] = None

    def set_result(self, value) -> None:
        self._value = value
        self._event.set()

    def set_exception(self, err: BaseException) -> None:
        self._error = err
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def cancel(self) -> bool:
        """Best-effort cancel: mark the request so the serving loop
        sheds it before dispatch. Returns ``False`` when the result (or
        a typed rejection) already landed; ``True`` marks it — but a
        dispatch already in flight may still deliver a result."""
        if self._event.is_set():
            return False
        self._cancelled = True
        return True

    def cancelled(self) -> bool:
        return self._cancelled

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                "serving request timed out (the request is STILL live — "
                "pass deadline_s= to submit() or call cancel() to bound "
                "the server's work, not just the wait)")
        if self._error is not None:
            raise self._error
        return self._value


class PredictServer:
    """Micro-batching serving front end over a :class:`CompiledPredictor`.

    ``max_batch`` defaults to the predictor's top bucket; ``window_s``
    and ``queue_depth`` default to their ``ALINK_TPU_SERVE_*`` flags.
    """

    def __init__(self, predictor: CompiledPredictor,
                 max_batch: Optional[int] = None,
                 window_s: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 min_fill: Optional[int] = None,
                 replicas: Optional[int] = None,
                 name: str = "serve"):
        self.predictor = predictor
        self.name = name
        self.max_batch = int(max_batch) if max_batch \
            else predictor.buckets[-1]
        self.window_s = serve_window_s() if window_s is None \
            else float(window_s)
        # adaptive batching: the loop dispatches as soon as the queue
        # drains (batch = everything that arrived during the previous
        # dispatch — size self-regulates to load, latency never waits
        # on hypothetical arrivals). min_fill > 1 (the
        # ALINK_TPU_SERVE_MIN_FILL flag) turns the latency budget on:
        # the loop holds an under-filled batch up to window_s for
        # stragglers (occupancy over latency).
        self.min_fill = serve_min_fill() if min_fill is None \
            else max(1, int(min_fill))
        depth = serve_queue_depth() if queue_depth is None \
            else int(queue_depth)
        self._ch = _Channel(max(1, depth), gauge_label=name)
        self._closed = threading.Event()
        self._stats_lock = threading.Lock()
        self._requests = 0
        self._failed = 0
        self._batches = 0
        self._occupancy_sum = 0.0
        self._latencies: deque = deque(maxlen=_P99_RING)
        # -- resilience (ISSUE 14) ------------------------------------
        self._shed = 0                 # deadline/cancel rejections
        self._fallback_batches = 0     # breaker-routed host-mapper serves
        self._respawns = 0             # supervised loop restarts
        self._quarantined = 0          # requests typed-failed by a crash
        self._breaker_lock = threading.Lock()
        self._breakers: dict = {}      # model version -> CircuitBreaker
        # cumulative opens/reopens/probes across ALL model versions (a
        # hot-swap storm retires breakers; the run's totals must not)
        self._breaker_totals = {"opens": 0, "reopens": 0, "probes": 0}
        # -- replica dispatch (ISSUE 11): R serving loops drain the ONE
        # admission channel and fan bucket batches out across the
        # session mesh's chips (one single-device model placement per
        # replica). ALINK_TPU_SERVE_REPLICAS=0 means one replica per
        # mesh device; a SHARDED predictor already spans every chip
        # with one program, so it always runs one loop.
        self.replicas = self._resolve_replicas(replicas)
        # admission warming (ISSUE 20): pre-install the predictor's
        # exported bucket x dtype grid from the AOT cache BEFORE the
        # admin readiness source is armed below — /readyz never flips
        # while first requests would still pay a cold compile the disk
        # already holds. No cache dir configured = exactly no work.
        self.warmed_programs = 0
        try:
            self.warmed_programs = predictor.warm_from_disk()
        except Exception as e:
            warnings.warn(f"serve:{name}: AOT admission warming failed "
                          f"({e!r}); serving opens cold", RuntimeWarning)
        self._threads = []
        for i in range(self.replicas):
            th = threading.Thread(
                target=self._run_replica, args=(i,), daemon=True,
                name=(f"alink-serve-{name}" if self.replicas == 1
                      else f"alink-serve-{name}-r{i}"))
            self._threads.append(th)
            th.start()
        # live operations plane (ISSUE 16): while ALINK_TPU_ADMIN_PORT
        # is armed, this server's breaker/admission state answers
        # /healthz for its lifetime (an open breaker = unhealthy AND
        # unready; closed at close()). Host-side only — the compiled
        # serving path never sees the endpoint.
        self._admin = acquire_admin(name)
        if self._admin is not None:
            self._admin.add_source(f"serve:{name}", self._readiness)
            self._admin.add_status(f"serve:{name}", self.stats)

    def _resolve_replicas(self, replicas: Optional[int]) -> int:
        from .sharded import serve_replicas
        r = serve_replicas() if replicas is None else int(replicas)
        if self.predictor.sharded:
            return 1            # the sharded program spans the mesh
        if r == 1:
            return 1            # the historical single loop
        # replicas fan out over the SESSION-mesh chips — 0 means one
        # per chip, an explicit count cycles the same device list (never
        # chips the session was configured to exclude)
        from ..common.mlenv import MLEnvironmentFactory
        devices = list(
            MLEnvironmentFactory.get_default().mesh.devices.reshape(-1))
        if r == 0:
            r = len(devices)
        self.predictor.ensure_replicas(
            [devices[i % len(devices)] for i in range(r)])
        return max(1, r)

    # -- submission (any thread) ----------------------------------------
    def submit(self, row: Tuple,
               deadline_s: Optional[float] = None) -> RequestFuture:
        """Enqueue one request row; blocks when the admission queue is
        full (backpressure). Raises after :meth:`close`.

        ``deadline_s`` is an END-TO-END budget stamped at admission: a
        request whose queue wait already exceeds it is SHED by the
        serving loop before the dispatch is paid — the future resolves
        to a typed :class:`~alink_tpu.serving.resilience.
        DeadlineExceeded`, and the compiled program never sees the row
        (counted in ``alink_serve_shed_total{reason="deadline"}``)."""
        if self._closed.is_set():
            raise RuntimeError(f"PredictServer {self.name!r} is closed")
        fut = RequestFuture(tuple(row), deadline_s=deadline_s)
        fut.ctx = reqtrace.admit()
        if not self._ch.put(fut):
            reqtrace.finish(fut.ctx, outcome="rejected_closed")
            raise RuntimeError(f"PredictServer {self.name!r} is closed")
        return fut

    def predict(self, row: Tuple, timeout: Optional[float] = None,
                deadline_s: Optional[float] = None) -> Tuple:
        """Synchronous single-request round trip."""
        return self.submit(row, deadline_s=deadline_s).result(timeout)

    def swap_model(self, model_table: MTable) -> int:
        """Hot-swap the served model (double-buffered; see predictor)."""
        return self.predictor.swap_model(model_table)

    # -- the serving loop (one per replica, supervised) -------------------
    def _run_replica(self, replica: int) -> None:
        """Supervisor: run the serving loop; when it CRASHES (an escape
        past :meth:`_serve`'s handling — e.g. an injected ``kill`` at
        ``serve.dispatch`` or a ``prefetch.get`` fault), quarantine the
        in-flight batch (every unresolved request fails with a typed
        :class:`~alink_tpu.serving.resilience.ReplicaCrashed` — never
        silence) and RESPAWN the loop. A respawned loop after
        :meth:`close` sees the channel sentinel and exits cleanly."""
        backoff = 0.01
        while True:
            inflight: List[RequestFuture] = []
            try:
                self._loop(replica, inflight)
                return
            except BaseException as e:
                # BaseException for the QUARANTINE (an interrupt must
                # not strand in-flight futures in silence) — but only
                # Exception respawns; KeyboardInterrupt / SystemExit
                # propagate after the quarantine (the feeder-
                # supervision rule)
                quarantined = [f for f in inflight if not f.done()]
                for f in quarantined:
                    f.set_exception(ReplicaCrashed(replica, e))
                    reqtrace.finish(f.ctx, outcome="replica_crashed")
                with self._stats_lock:
                    self._failed += len(quarantined)
                    self._quarantined += len(quarantined)
                    self._respawns += 1
                trace_instant("serve.respawn", cat="serve",
                              args={"server": self.name, "replica": replica,
                                    "quarantined": len(quarantined),
                                    "error": type(e).__name__})
                if metrics_enabled():
                    get_registry().inc("alink_serve_loop_respawns_total", 1,
                                       {"server": self.name})
                if not isinstance(e, Exception):
                    raise
                time.sleep(backoff)
                backoff = min(0.5, backoff * 2)

    def _loop(self, replica: int, inflight: List[RequestFuture]) -> None:
        while True:
            del inflight[:]
            first = self._ch.get()
            if first is _SENTINEL:
                return
            inflight.append(first)
            if first.ctx is not None:
                first.ctx.mark("dequeue")
            deadline = None
            closing = False
            while len(inflight) < self.max_batch:
                got = self._ch.drain(self.max_batch - len(inflight))
                if got:
                    inflight.extend(got)
                    for f in got:
                        if f.ctx is not None:
                            f.ctx.mark("dequeue")
                    continue
                # queue drained: dispatch NOW unless the batch is under
                # min_fill and latency budget remains
                if len(inflight) >= self.min_fill:
                    break
                if deadline is None:
                    deadline = time.monotonic() + self.window_s
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                nxt = self._ch.get(timeout=remaining)
                if nxt is _EMPTY:
                    break
                if nxt is _SENTINEL:
                    closing = True
                    break
                inflight.append(nxt)
                if nxt.ctx is not None:
                    nxt.ctx.mark("dequeue")
            self._serve(inflight, replica)
            if closing:
                return

    # -- deadline / cancellation shedding ---------------------------------
    def _admit(self, batch: List[RequestFuture],
               now: float) -> List[RequestFuture]:
        """Shed requests whose queue wait already exceeds their deadline
        (or that the submitter cancelled) BEFORE the dispatch is paid:
        the typed rejection lands through the future, the compiled
        program never sees the row."""
        kept: List[RequestFuture] = []
        for fut in batch:
            if fut.cancelled():
                fut.set_exception(RequestCancelled(
                    "request cancelled before dispatch"))
                self._record_shed("cancelled")
                reqtrace.finish(fut.ctx, outcome="shed_cancelled")
                continue
            dl = fut.deadline_s
            if dl is not None:
                waited = now - fut.submitted_at
                if waited > dl:
                    fut.set_exception(DeadlineExceeded(waited, dl))
                    self._record_shed("deadline")
                    reqtrace.finish(fut.ctx, outcome="shed_deadline")
                    continue
            kept.append(fut)
        return kept

    def _record_shed(self, reason: str) -> None:
        with self._stats_lock:
            self._shed += 1
        record_shed(self.name, reason)

    # -- circuit-broken dispatch ------------------------------------------
    def _breaker_for(self, version: int) -> CircuitBreaker:
        """The ACTIVE model version's breaker (a hot swap starts the new
        version closed — per-model-version state, the PR 11 fallback
        upgraded to a recovering policy). Old versions' breakers are
        dropped; a replica mid-dispatch on one keeps its own reference."""
        with self._breaker_lock:
            br = self._breakers.get(version)
            if br is None:
                for old in self._breakers.values():   # retire, keep totals
                    old.retire()    # a stale in-flight verdict must not
                                    # move the gauge or post-snapshot
                                    # counters (frozen from here on)
                    s = old.snapshot()
                    for k in self._breaker_totals:
                        self._breaker_totals[k] += s[k]
                br = CircuitBreaker(self.name, version)
                self._breakers = {version: br}
            return br

    def breaker_stats(self) -> dict:
        """state/step of the ACTIVE version's breaker plus cumulative
        opens/reopens/probes across every version this server served
        (zeros when the breaker never engaged)."""
        with self._breaker_lock:
            brs = list(self._breakers.values())
            totals = dict(self._breaker_totals)
        if not brs:
            return {"state": "closed", "step": 0, "version": None,
                    **totals}
        snap = brs[-1].snapshot()
        for k in totals:
            snap[k] = snap[k] + totals[k]
        return snap

    def _serve(self, batch: List[RequestFuture], replica: int = 0) -> None:
        batch = self._admit(batch, time.perf_counter())
        if not batch:
            return
        # the batch is assembled: the window hold / micro-batch
        # coalescing ends here, dispatch work begins — the mark that
        # closes the admission->dispatch queue wait
        ctxs = [f.ctx for f in batch if f.ctx is not None]
        for c in ctxs:
            c.mark("coalesce")
        done_t = None
        route, br, settled = "compiled", None, False
        if serve_breaker_enabled():
            br = self._breaker_for(self.predictor.model_version)
            route = br.acquire()

        def _settle_failure() -> None:
            # an escape (injected kill, encode error, fan-out error)
            # past the paired on_success/on_failure MUST still release
            # the breaker slot: a leaked half-open probe would wedge
            # the breaker in fallback forever (no caller left to close
            # or re-open it)
            nonlocal settled
            if br is not None and route != "fallback" and not settled:
                settled = True
                br.on_failure(probe=(route == "probe"))
        try:
            data = MTable([f.row for f in batch],
                          self.predictor.data_schema)
            if route == "fallback":
                out = self._fallback(data)
            else:
                try:
                    with reqtrace.batch_scope(ctxs):
                        out = self.predictor.predict_table(
                            data, replica=replica)
                    if br is not None:
                        settled = True
                        br.on_success(probe=(route == "probe"))
                except FaultInjected:
                    raise       # the injected process kill: the loop
                                # supervisor quarantines + respawns
                except Exception as e:
                    if br is None:
                        raise
                    settled = True
                    br.on_failure(probe=(route == "probe"))
                    if route == "probe":
                        # degraded traffic stays degraded on a failed
                        # probe — the batch serves through the host
                        # mapper instead of paying for the re-test
                        out = self._fallback(data)
                    else:
                        raise   # closed-state failure: the batch fails
                                # its own requests (pre-resilience
                                # contract) while the breaker counts
            # vectorized fan-out: pull the output columns once, hand
            # each future its row tuple (out.row(i) would re-resolve
            # every column per request)
            cols = [out.col(nm) for nm in out.col_names]
            done_t = time.perf_counter()
            for i, fut in enumerate(batch):
                fut.set_result(tuple(c[i] for c in cols))
        except FaultInjected:
            _settle_failure()
            raise
        except BaseException as e:
            _settle_failure()
            done_t = done_t or time.perf_counter()
            for fut in batch:
                if not fut.done():
                    fut.set_exception(e)
            with self._stats_lock:
                self._failed += len(batch)
        self._account(batch, done_t)

    def _fallback(self, data: MTable) -> MTable:
        """Breaker-open degradation: the batch serves through the HOST
        mapper path (the active model applied off-device) — degraded
        throughput, correct answers, zero dropped requests."""
        out = self.predictor.host_reference(data)
        with self._stats_lock:
            self._fallback_batches += 1
        if metrics_enabled():
            get_registry().inc("alink_serve_breaker_fallback_total", 1,
                               {"server": self.name})
        return out

    def _account(self, batch: List[RequestFuture], done_t: float) -> None:
        n = len(batch)
        occupancy = n / self.predictor.bucket_for(n)
        lats = [done_t - f.submitted_at for f in batch]
        with self._stats_lock:
            self._requests += n
            self._batches += 1
            self._occupancy_sum += occupancy
            self._latencies.extend(lats)
            refresh = self._requests % _P99_EVERY < n
            p99 = _percentile(list(self._latencies), 99.0) if refresh else None
        rec = metrics_enabled()
        reg = get_registry() if rec else None
        lbl = {"server": self.name}
        for fut, dt in zip(batch, lats):
            ctx = fut.ctx
            if ctx is None:
                trace_complete("serve.request", dt, cat="serve",
                               args={"batch_rows": n})
                continue
            # the admission->dispatch queue wait ends at the coalesce
            # mark (batch assembled, dispatch work starting)
            qwait = ctx.phase_end("coalesce")
            outcome = ("ok" if fut._error is None
                       else type(fut._error).__name__)
            reqtrace.finish(ctx, outcome=outcome)
            if rec:
                # the exemplar links the p99 bucket to THIS request's
                # timeline (one bounded slot per bucket)
                ex = {"trace_id": ctx.trace_id}
                if ctx.tenant is not None:
                    ex["tenant"] = ctx.tenant
                reg.observe("alink_serve_request_seconds", dt, lbl,
                            exemplar=ex)
                if qwait is not None:
                    reg.observe("alink_serve_queue_wait_seconds", qwait,
                                lbl, exemplar=ex)
        if rec:
            reg.inc("alink_serve_requests_total", n, lbl)
            reg.set_gauge("alink_serve_queue_depth", self._ch.depth(), lbl)
            if p99 is not None:
                reg.set_gauge("alink_serve_p99_seconds", p99, lbl)
                self.predictor.flush_metrics()

    # -- stats / shutdown -------------------------------------------------
    def _readiness(self) -> dict:
        """ReadinessSource for the admin plane (ISSUE 16): the serving
        tier is healthy/ready while it admits requests AND the active
        model version's circuit breaker is not OPEN — an open breaker
        means requests are being answered by the degraded host-mapper
        fallback (or typed-failed), which an operator must see as 503
        on /healthz while it lasts."""
        admitting = not self._closed.is_set()
        breaker = self.breaker_stats()
        ok = admitting and breaker.get("state") != OPEN
        return {"ready": ok, "healthy": ok,
                "admission_open": admitting,
                "breaker": breaker,
                "queue_depth": self._ch.depth(),
                "model_version": self.predictor.model_version}

    def stats(self) -> dict:
        """A point-in-time snapshot: request/batch counts, mean batch
        occupancy, rolling p50/p99, program-cache hit rate, plus the
        resilience counters (shed, breaker fallbacks, loop respawns)."""
        with self._stats_lock:
            lats = list(self._latencies)
            requests, failed = self._requests, self._failed
            batches, occ = self._batches, self._occupancy_sum
            shed, fb = self._shed, self._fallback_batches
            respawns, quarantined = self._respawns, self._quarantined
        cache = self.predictor.cache_stats()
        looked = cache["hits"] + cache["misses"]
        return {
            "requests": requests, "failed": failed, "batches": batches,
            "mean_batch_rows": (requests / batches) if batches else 0.0,
            "mean_occupancy": (occ / batches) if batches else 0.0,
            "p50_s": _percentile(lats, 50.0),
            "p99_s": _percentile(lats, 99.0),
            "bucket_hit_rate": (cache["hits"] / looked) if looked else 0.0,
            "programs": cache["programs"],
            "model_version": self.predictor.model_version,
            "queue_depth": self._ch.depth(),
            "shed": shed, "fallback_batches": fb,
            "loop_respawns": respawns, "quarantined": quarantined,
            "warmed_programs": self.warmed_programs,
            "breaker": self.breaker_stats(),
        }

    def close(self, timeout: float = 10.0) -> None:
        """Stop admitting, drain queued requests, join the loop(s)."""
        if self._closed.is_set():
            return
        self._closed.set()
        if self._admin is not None:
            self._admin.remove_source(f"serve:{self.name}")
            self._admin.remove_status(f"serve:{self.name}")
            self._admin = None
            release_admin()
        self._ch.close()
        deadline = time.monotonic() + timeout
        for th in self._threads:
            th.join(max(0.0, deadline - time.monotonic()))

    def __enter__(self) -> "PredictServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _FeederSupervision:
    """The shared feeder supervision policy (ISSUE 14): bounded
    retry + doubling backoff for TRANSIENT swap failures, skip-and-
    record for POISONED snapshots (corrupt payload, geometry refusal —
    deterministic, retrying cannot help), and the last-good-model
    guarantee — a swap that never succeeds never flips the active
    version, so the server keeps serving the previous model, never a
    torn or absent one. Every error is visible AT THE FAILURE
    (``alink_serve_feeder_errors_total`` + one RuntimeWarning per
    feeder+kind), not only at the deferred ``join()``."""

    #: set by subclasses for metric labels / warnings
    feeder_kind = "feeder"

    retried = 0          # transient retries spent
    skipped = 0          # poisoned snapshots skipped

    def _supervised_swap(self, swap: Callable[[], int]) -> Optional[int]:
        """Run one swap attempt under supervision; returns the new
        version, or ``None`` when the snapshot was skipped (poisoned /
        budget exhausted) — the caller moves on to the next snapshot
        with the last good model still serving."""
        budget = feeder_retries()
        backoff = feeder_backoff_s()
        attempt = 0
        while True:
            try:
                return swap()
            except FaultInjected:
                raise            # the injected process kill passes through
            except Exception as e:
                # Exception, NOT BaseException: a KeyboardInterrupt /
                # SystemExit must propagate immediately, not sleep
                # through retry cycles misrecorded as a backend blip
                kind = classify_feeder_error(e)
                record_feeder_error(self.feeder_kind, kind, e)
                if kind == "poisoned":
                    self.skipped += 1
                    return None
                attempt += 1
                if attempt > budget:
                    raise        # the run loop records this as "fatal"
                self.retried += 1
                if metrics_enabled():
                    get_registry().inc(
                        "alink_serve_feeder_retries_total", 1,
                        {"feeder": self.feeder_kind})
                time.sleep(backoff)
                backoff *= 2


class ModelStreamFeeder(_FeederSupervision):
    """Tap a model-snapshot stream into a server's hot-swap path.

    Drains ``stream_op.timed_batches()`` on a background thread and
    calls ``server.swap_model`` per snapshot — the serving-tier end of
    the FTRL trainer's model stream (reference: ``FtrlPredictStreamOp``'s
    CollectModel swap). Keeps every swapped model table (``versions``)
    so a bench/test can re-validate responses against the exact model
    set that was ever active.

    Swaps run SUPERVISED (:class:`_FeederSupervision`): transient
    failures retry with bounded backoff, poisoned snapshots skip with
    the error recorded, and in both cases the server keeps serving the
    last good model. A stream-side error still ends the feeder — but it
    is recorded at the failure, not only at ``join()``."""

    feeder_kind = "ModelStreamFeeder"

    def __init__(self, server: PredictServer, stream_op,
                 limit: Optional[int] = None,
                 on_swap: Optional[Callable[[int, MTable], None]] = None):
        self.server = server
        self.stream_op = stream_op
        self.limit = limit
        self.on_swap = on_swap
        self.versions: List[Tuple[int, MTable]] = []
        self.error: Optional[BaseException] = None
        self.retried = 0
        self.skipped = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="alink-serve-feeder")

    def start(self) -> "ModelStreamFeeder":
        self._thread.start()
        return self

    def _run(self) -> None:
        try:
            for _t, model_table in self.stream_op.timed_batches():
                version = self._supervised_swap(
                    lambda: self.server.swap_model(model_table))
                if version is None:
                    continue     # poisoned snapshot skipped; last good
                                 # model keeps serving
                self.versions.append((version, model_table))
                trace_instant("serve.model_stream", cat="serve",
                              args={"version": version})
                if self.on_swap is not None:
                    self.on_swap(version, model_table)
                if self.limit is not None \
                        and len(self.versions) >= self.limit:
                    return
        except BaseException as e:   # surfaced via join() AND recorded now
            self.error = e
            if not getattr(e, "_alink_feeder_recorded", False):
                record_feeder_error(self.feeder_kind, "fatal", e)

    def run(self) -> int:
        """Drain the model stream synchronously on the caller's thread
        (the online DAG's train-stage supervisor owns the drain and
        needs the crash to surface HERE, not on a daemon thread);
        returns the swap count."""
        self._run()
        if self.error is not None:
            raise self.error
        return len(self.versions)

    def join(self, timeout: Optional[float] = None) -> int:
        """Wait for the stream to drain; returns the swap count. Raises
        the feeder thread's error, if any — and refuses to return a
        PARTIAL count: a feeder still swapping past the timeout would
        silently invalidate any caller that snapshots ``versions``."""
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError(
                f"ModelStreamFeeder still draining after {timeout}s "
                f"({len(self.versions)} swaps so far); the model stream "
                f"has not ended — the swap count and version set are "
                f"incomplete")
        if self.error is not None:
            raise self.error
        return len(self.versions)


class DeviceWeightsFeeder(_FeederSupervision):
    """Device-to-device model swaps off the FTRL trainer's (z, n) state
    (ROADMAP item 1 leftover, ISSUE 12 satellite).

    :class:`ModelStreamFeeder` round-trips every snapshot through a host
    model table — the trainer fetches its device weights to host, builds
    rows, and ``swap_model`` re-places them on the mesh. This feeder
    removes the round trip end-to-end: it registers itself as the
    trainer's ``set_device_snapshot_consumer`` hook, receives the LIVE
    device weight vector at each emission boundary, reshapes it to the
    active serving kernel's geometry WITH DEVICE OPS ONLY (slice + pad —
    no ``device_get``, no host staging array), and installs it through
    ``CompiledPredictor.swap_weights`` (same-geometry in-place swap,
    ``jax.device_put`` into a matched placement is device-to-device).
    The served scores are bitwise identical to the host-table path —
    both serve the same weight values through the same compiled bucket
    programs (tests/test_serving.py pins zero host traffic AND score
    parity).

    The trainer must serve the SAME geometry the predictor was built
    with (the warm-start model): a layout the feeder cannot map refuses
    loudly via ``swap_weights``'s geometry check. Drive the drain with
    :meth:`run` (the hook consumes every snapshot, so the stream yields
    nothing — iterating it IS the training loop)."""

    feeder_kind = "DeviceWeightsFeeder"

    def __init__(self, server: PredictServer, ftrl_op,
                 limit: Optional[int] = None,
                 on_swap: Optional[Callable[[int], None]] = None):
        self.server = server
        self.ftrl_op = ftrl_op
        self.limit = limit
        self.on_swap = on_swap
        self.versions: List[int] = []
        self.error: Optional[BaseException] = None
        self.retried = 0
        self.skipped = 0
        self._thread = threading.Thread(target=self._drain, daemon=True,
                                        name="alink-serve-devfeeder")
        ftrl_op.set_device_snapshot_consumer(self._consume)

    # -- the trainer-side hook (runs on the draining thread) -------------
    def _consume(self, w_full, info: dict) -> bool:
        if self.limit is not None and len(self.versions) >= self.limit:
            return False           # past the cap: host path resumes
        import jax.numpy as jnp
        kernel = self.server.predictor._active.kernel
        wf8_len = int(kernel.model_arrays[0].shape[0])
        dim, fb_S = int(info["dim"]), info.get("fb_S")
        # the trainer's snapshot() layout logic, as device slices
        if info.get("has_intercept"):
            b = w_full[0]
            feats = (w_full[1:dim] if fb_S is None
                     else w_full[fb_S:fb_S + dim - 1])
        else:
            b = jnp.zeros((), w_full.dtype)
            feats = w_full[:dim]
        if int(feats.shape[0]) > wf8_len:
            # the documented loud refusal: a trainer wider than the
            # serving kernel's weight slot must not die in a jnp shape
            # error on the drain thread — recorded at the failure
            # (metric + one-time warning), then raised
            err = ValueError(
                f"DeviceWeightsFeeder geometry mismatch: trainer emits "
                f"{int(feats.shape[0])} feature weights, the active "
                f"serving kernel holds {wf8_len} — a different geometry "
                f"must go through swap_model (new signature, new "
                f"programs)")
            # kind="fatal", not "poisoned": the documented loud refusal
            # KILLS the drain (a wiring bug, not a per-snapshot poison
            # the supervision could skip past) — the metric must say so
            record_feeder_error(self.feeder_kind, "fatal", err)
            err._alink_feeder_recorded = True   # _drain must not record
            raise err                           # the SAME event twice
        wf8 = jnp.zeros(wf8_len, w_full.dtype).at[:feats.shape[0]].set(feats)
        version = self._supervised_swap(
            lambda: self.server.predictor.swap_weights((wf8, b)))
        if version is None:
            return True    # poisoned swap skipped (recorded); the last
                           # good model keeps serving
        self.versions.append(version)
        trace_instant("serve.model_stream", cat="serve",
                      args={"version": version, "path": "device"})
        if self.on_swap is not None:
            self.on_swap(version)
        return True

    def _drain(self) -> None:
        try:
            # the hook consumes every emission, so this loop only DRIVES
            # training; nothing crosses to host
            for _ in self.ftrl_op.timed_batches():
                pass
        except BaseException as e:   # surfaced via join() AND recorded now
            self.error = e
            if not getattr(e, "_alink_feeder_recorded", False):
                record_feeder_error(self.feeder_kind, "fatal", e)

    def start(self) -> "DeviceWeightsFeeder":
        self._thread.start()
        return self

    def run(self) -> int:
        """Drain synchronously on the caller's thread; returns the swap
        count."""
        self._drain()
        if self.error is not None:
            raise self.error
        return len(self.versions)

    def join(self, timeout: Optional[float] = None) -> int:
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError(
                f"DeviceWeightsFeeder still draining after {timeout}s "
                f"({len(self.versions)} swaps so far)")
        if self.error is not None:
            raise self.error
        return len(self.versions)
