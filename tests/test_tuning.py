"""GridSearchCV / GridSearchTVSplit tests (reference pipeline/tuning/
GridSearchCVTest pattern: grid over a regularization knob, assert the
winning candidate and that the tuned model predicts)."""

import numpy as np
import pytest

from alink_tpu.operator.batch.source import MemSourceBatchOp
from alink_tpu.pipeline import (BinaryClassificationTuningEvaluator,
                                GridSearchCV, GridSearchTVSplit, ParamGrid,
                                RegressionTuningEvaluator)
from alink_tpu.pipeline.base import Pipeline
from alink_tpu.pipeline.classification import LogisticRegression
from alink_tpu.pipeline.regression import LinearRegression


def _binary_src(n=240, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 3)
    y = (X @ np.asarray([2.0, -1.0, 0.5]) + 0.3 * rng.randn(n) > 0).astype(int)
    rows = [tuple(x) + (int(t),) for x, t in zip(X, y)]
    return MemSourceBatchOp(rows, "f0 DOUBLE, f1 DOUBLE, f2 DOUBLE, label INT")


def test_grid_search_cv_binary():
    src = _binary_src()
    lr = LogisticRegression(feature_cols=["f0", "f1", "f2"], label_col="label",
                            prediction_col="pred",
                            prediction_detail_col="details", max_iter=30)
    grid = ParamGrid().add_grid(lr, "l2", [0.0001, 100.0])
    cv = GridSearchCV(estimator=lr, param_grid=grid,
                      tuning_evaluator=BinaryClassificationTuningEvaluator(
                          label_col="label", prediction_detail_col="details"),
                      num_folds=3, seed=1)
    model = cv.fit(src)
    # tiny L2 must beat the absurd one on AUC
    assert "l2=0.0001" in model.best_params_desc
    report = model.report.to_mtable()
    assert report.num_rows == 2
    out = model.transform(src).collect_mtable()
    acc = (np.asarray(out.col("pred")) == np.asarray(out.col("label"))).mean()
    assert acc > 0.9


def test_grid_search_tv_split_regression_pipeline():
    rng = np.random.RandomState(3)
    X = rng.randn(200, 2)
    y = X @ np.asarray([1.5, -2.0]) + 0.1 * rng.randn(200)
    rows = [tuple(x) + (float(t),) for x, t in zip(X, y)]
    src = MemSourceBatchOp(rows, "a DOUBLE, b DOUBLE, y DOUBLE")
    reg = LinearRegression(feature_cols=["a", "b"], label_col="y",
                           prediction_col="pred")
    grid = ParamGrid().add_grid(reg, "l2", [0.0, 1000.0])
    tv = GridSearchTVSplit(estimator=Pipeline(reg), param_grid=grid,
                           tuning_evaluator=RegressionTuningEvaluator(
                               label_col="y", prediction_col="pred",
                               tuning_regression_metric="RMSE"),
                           train_ratio=0.75, seed=5)
    model = tv.fit(src)
    assert "l2=0.0" in model.best_params_desc
    out = model.transform(src).collect_mtable()
    rmse = float(np.sqrt(np.mean((np.asarray(out.col("pred"))
                                  - np.asarray(out.col("y"))) ** 2)))
    assert rmse < 0.5
