"""CompiledPredictor — per-model jitted serving programs, shape-bucketed.

The reference applies a model per row through ``ModelMapperAdapter.map``
(common/mapper/ModelMapperAdapter.java:42-45); the mappers here are
batched but HOST-side numpy. Serving traffic needs the score kernel on
the device without paying one XLA compile per request size, so:

* a :class:`ServingKernel` (built by the mapper, ``Mapper.
  serving_kernel()``) splits model application into ``encode`` (host:
  rows -> padded arrays), ``device_fn`` (pure jittable scoring) and
  ``decode`` (host: device scores -> output table, the mapper's own
  label/detail logic);
* the predictor compiles ``device_fn`` once per **(model signature,
  encoding kind, shape bucket)** — request batches pad with zero rows to
  the smallest covering bucket from ``ALINK_TPU_SERVE_BUCKETS``, so a
  handful of programs cover arbitrary request sizes and every program
  is reused across requests AND across hot-swapped models of the same
  geometry (weights are *arguments*, never baked into the trace);
* padding rows are numerical no-ops: per-row scoring is row-independent,
  so the real rows of a padded batch are bitwise-identical to the same
  rows served unpadded (tests/test_serving.py pins it).

Hot model swap is double-buffered: :meth:`CompiledPredictor.swap_model`
builds the new model version — mapper load, kernel extraction,
``device_put`` of the weights — entirely in the *standby* slot on the
caller's thread, then flips the active-slot reference atomically.  A
dispatch in flight keeps its own reference to the version it started
with, so no request ever sees a torn model and a swap never blocks the
serving loop.

Cache-key discipline: the serving program cache keys on (model
signature, kind, bucket, encoded shapes/dtypes) — everything that can
change a compiled program is IN the key, so the ``ALINK_TPU_SERVE_*``
flags are declared key-neutral in ``common/flags.py`` and alink-lint's
ENV-KEY-FOLD rule checks this module as a factory root.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from ..common.metrics import get_registry, metrics_enabled
from ..common.mtable import MTable
from ..common.tracing import trace_complete, trace_span

DEFAULT_BUCKETS = (1, 8, 32, 128, 512)


def serve_compiled_enabled() -> bool:
    """``ALINK_TPU_SERVE_COMPILED``: route the stream predict twins
    (ModelMapStreamOp) through the compiled serving path. Default off —
    the flag-off path runs the exact pre-serving host mapper code."""
    from ..common.flags import flag_value
    return flag_value("ALINK_TPU_SERVE_COMPILED", False)


def serve_buckets(default: Sequence[int] = DEFAULT_BUCKETS) -> Tuple[int, ...]:
    """``ALINK_TPU_SERVE_BUCKETS``: the shape-bucket set, sorted unique
    positive ints (comma-separated). The registry parser normalizes;
    this accessor returns the tuple call sites key programs on."""
    from ..common.flags import flag_value
    raw = flag_value("ALINK_TPU_SERVE_BUCKETS", "")
    if not raw:
        return tuple(default)
    return _parse_buckets(raw) or tuple(default)


def serve_window_s() -> float:
    """``ALINK_TPU_SERVE_WINDOW_MS`` (batching latency budget) in
    seconds."""
    from ..common.flags import flag_value
    return float(flag_value("ALINK_TPU_SERVE_WINDOW_MS", 2.0)) / 1e3


def serve_min_fill() -> int:
    """``ALINK_TPU_SERVE_MIN_FILL``: the micro-batcher's fill target —
    batches below it are held up to the window for stragglers. The
    default of 1 keeps pure adaptive dispatch."""
    from ..common.flags import flag_value
    return int(flag_value("ALINK_TPU_SERVE_MIN_FILL", 1))


def serve_queue_depth() -> int:
    """``ALINK_TPU_SERVE_QUEUE``: admission-control bound of the request
    channel (requests beyond it block the submitter — backpressure)."""
    from ..common.flags import flag_value
    return int(flag_value("ALINK_TPU_SERVE_QUEUE", 1024))


def serve_swap_mode() -> str:
    """``ALINK_TPU_SERVE_SWAP``: ``double`` (default — standby slot
    prepared off the serving loop, atomic flip) or ``sync`` (the flip
    additionally blocks until the standby weights are device-resident;
    debugging aid, serving loop still never blocks)."""
    from ..common.flags import flag_value
    return str(flag_value("ALINK_TPU_SERVE_SWAP", "double"))


def _parse_buckets(raw: str) -> Tuple[int, ...]:
    out = []
    for part in str(raw).split(","):
        part = part.strip()
        if not part:
            continue
        out.append(int(part))
    return tuple(sorted({b for b in out if b > 0}))


@dataclass
class ServingKernel:
    """One model's compiled-serving contract (built by the mapper).

    ``signature``     — hashable PROGRAM identity: geometry/dtype/kind of
                        the model, everything that shapes the traced
                        computation EXCEPT the weight values. Two model
                        versions with equal signatures share compiled
                        programs (the hot-swap fast path).
    ``model_arrays``  — the weights, a tuple of host arrays; the
                        predictor ``device_put``s them once per model
                        version and passes them as program arguments.
    ``encode(mt, bucket)`` -> ``(kind, arrays)`` — host encode of a
                        request table, padded with zero rows to
                        ``bucket``; ``kind`` discriminates encodings
                        (dense vs sparse) of the same model.
    ``device_fns[kind](model_arrays, *arrays)`` — pure jittable scoring;
                        outputs are arrays whose leading axis is rows.
    ``decode(outputs, mt)`` — host decode of the REAL-row slice of the
                        program outputs into the mapper's output table
                        (the mapper's own label/detail logic).
    """
    signature: Tuple
    model_arrays: Tuple[np.ndarray, ...]
    encode: Callable[[MTable, int], Tuple[str, Tuple[np.ndarray, ...]]]
    device_fns: Dict[str, Callable]
    decode: Callable[[Tuple[np.ndarray, ...], MTable], MTable]


def _merge_parts(parts):
    """Concatenate chunk outputs column-wise in ONE pass — a pairwise
    ``concat_rows`` fold re-copies the growing table per part, O(p^2)
    data movement on the routed-stream hot path."""
    first = parts[0]
    cols = {}
    for nm in first.col_names:
        arrs = []
        for p in parts:
            c = p.col(nm)
            if getattr(c, "__mtable_column__", False):
                c = c.materialize()
            arrs.append(c)
        if any(a.dtype == object for a in arrs):
            out = np.empty(sum(a.shape[0] for a in arrs), object)
            off = 0
            for a in arrs:
                out[off:off + a.shape[0]] = a
                off += a.shape[0]
        else:
            out = np.concatenate(arrs)
        cols[nm] = out
    return MTable(cols, first.schema)


class _ModelVersion:
    """One immutable model slot: kernel + device-resident weights."""

    __slots__ = ("version", "kernel", "device_arrays", "mapper")

    def __init__(self, version: int, kernel: ServingKernel, mapper=None):
        import jax
        self.version = version
        self.kernel = kernel
        self.mapper = mapper
        # the weights land on device HERE — on the swapping thread, not
        # the serving loop (the double-buffer contract)
        self.device_arrays = tuple(jax.device_put(a)
                                   for a in kernel.model_arrays)


class CompiledPredictor:
    """Shape-bucketed compiled model application with hot swap.

    ``CompiledPredictor(mapper)`` takes a LOADED ModelMapper that
    implements ``serving_kernel()``; :meth:`for_mapper` returns ``None``
    instead of raising for mappers without a kernel (the stream-twin
    routing falls back to the host path).
    """

    def __init__(self, mapper, buckets: Optional[Sequence[int]] = None,
                 name: str = "serve"):
        kernel = mapper.serving_kernel()
        if kernel is None:
            raise TypeError(
                f"{type(mapper).__name__} does not provide a serving "
                f"kernel; use CompiledPredictor.for_mapper() to fall "
                f"back to the host mapper path")
        self.name = name
        self._buckets = tuple(sorted({int(b) for b in buckets if int(b) > 0})) \
            if buckets else serve_buckets()
        if not self._buckets:
            raise ValueError("empty bucket set")
        self._swap_lock = threading.Lock()
        self._cache_lock = threading.Lock()
        self._programs: Dict[Tuple, Callable] = {}
        self._hits = 0
        self._hits_reported = 0
        self._misses = 0
        self._versions = 0
        # slot 0 = active. The standby slot is materialized per swap
        # (a fresh _ModelVersion) and flipped in by ONE reference store,
        # so readers racing a swap see either the old or the new version
        # whole — never a mix.
        self._active = self._make_version(kernel, mapper)

    # ------------------------------------------------------------------
    @classmethod
    def for_mapper(cls, mapper, buckets: Optional[Sequence[int]] = None,
                   name: str = "serve") -> Optional["CompiledPredictor"]:
        """A predictor, or ``None`` when the mapper has no kernel."""
        try:
            kernel = mapper.serving_kernel()
        except RuntimeError:
            kernel = None
        if kernel is None:
            return None
        return cls(mapper, buckets=buckets, name=name)

    def _make_version(self, kernel: ServingKernel, mapper) -> _ModelVersion:
        self._versions += 1
        return _ModelVersion(self._versions, kernel, mapper)

    # -- model hot swap -------------------------------------------------
    def swap_model(self, model_table: MTable) -> int:
        """Load ``model_table`` into the standby slot and flip it active.

        Runs entirely on the caller's thread (the model-stream tap):
        mapper construction, ``load_model``, kernel extraction and the
        weight ``device_put`` all happen BEFORE the flip, which is one
        atomic reference store. Returns the new version number.
        Serialized across swappers; never blocks the serving loop."""
        with self._swap_lock:
            t0 = time.perf_counter()
            with trace_span("serve.swap", cat="serve"):
                base = self._active.mapper
                mapper = type(base)(model_table.schema, base.data_schema,
                                    base.params)
                mapper.load_model(model_table)
                standby = self._make_version(mapper.serving_kernel(), mapper)
                if serve_swap_mode() == "sync":
                    import jax
                    jax.block_until_ready(standby.device_arrays)
                self._active = standby     # the atomic flip
            dt = time.perf_counter() - t0
        if metrics_enabled():
            reg = get_registry()
            reg.inc("alink_serve_model_swaps_total", 1,
                    {"predictor": self.name})
            reg.observe("alink_serve_swap_seconds", dt,
                        {"predictor": self.name})
        return standby.version

    @property
    def model_version(self) -> int:
        return self._active.version

    @property
    def buckets(self) -> Tuple[int, ...]:
        return self._buckets

    # -- program cache --------------------------------------------------
    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (requests larger than the top bucket are
        served in top-bucket chunks)."""
        for b in self._buckets:
            if n <= b:
                return b
        return self._buckets[-1]

    def _program(self, ver: _ModelVersion, kind: str, bucket: int,
                 arrays: Tuple[np.ndarray, ...]) -> Callable:
        """The compiled program for (model signature, kind, bucket) —
        every dimension that shapes the trace is part of the key
        (leading axes are the bucket itself; dtypes are fixed by the
        kernel signature), so a cache hit can never serve a stale
        program. The hit path is lock-free (GIL-atomic dict read + int
        bump) — it runs per dispatched batch on the serving loop."""
        key = (ver.kernel.signature, kind, bucket,
               tuple(a.shape[1:] for a in arrays))
        prog = self._programs.get(key)
        if prog is not None:
            self._hits += 1
            return prog
        import jax
        with self._cache_lock:
            prog = self._programs.get(key)
            if prog is None:
                self._misses += 1
                prog = jax.jit(ver.kernel.device_fns[kind])
                self._programs[key] = prog
                if metrics_enabled():
                    get_registry().inc("alink_serve_program_cache_total",
                                       1, {"result": "miss",
                                           "predictor": self.name})
            else:
                self._hits += 1
        return prog

    def cache_stats(self) -> Dict[str, int]:
        self.flush_metrics()
        with self._cache_lock:
            return {"hits": self._hits, "misses": self._misses,
                    "programs": len(self._programs)}

    def flush_metrics(self) -> None:
        """Push the (lock-free) hit counter delta into the registry —
        per-hit registry updates would tax every dispatched batch, so
        hits batch up and flush at stats/accounting boundaries."""
        if not metrics_enabled():
            return
        with self._cache_lock:
            delta = self._hits - self._hits_reported
            self._hits_reported = self._hits
        if delta > 0:
            get_registry().inc("alink_serve_program_cache_total", delta,
                               {"result": "hit", "predictor": self.name})

    # -- prediction -----------------------------------------------------
    def predict_table(self, data: MTable) -> MTable:
        """Serve a whole request table through the bucketed programs.

        Output is bitwise-identical for the real rows no matter which
        bucket (or chunk split) served them — padding rows are zero and
        per-row scoring is row-independent."""
        n = data.num_rows
        if n == 0:
            return self._active.mapper.map_table(data)
        top = self._buckets[-1]
        if n <= top:
            return self._predict_chunk(data)
        parts = [self._predict_chunk(data.take_rows(np.arange(s, min(s + top, n))))
                 for s in range(0, n, top)]
        return _merge_parts(parts)

    def _predict_chunk(self, data: MTable) -> MTable:
        import jax
        t0 = time.perf_counter()
        ver = self._active           # one consistent model per dispatch
        n = data.num_rows
        bucket = self.bucket_for(n)
        kind, arrays = ver.kernel.encode(data, bucket)
        prog = self._program(ver, kind, bucket, arrays)
        out = prog(ver.device_arrays, *arrays)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        # ONE batched host fetch, then slice the padding rows off
        host = jax.device_get(list(out))
        sliced = tuple(np.asarray(a)[:n] for a in host)
        result = ver.kernel.decode(sliced, data)
        trace_complete("serve.batch", time.perf_counter() - t0, cat="serve",
                       args={"rows": n, "bucket": bucket,
                             "model_version": ver.version})
        if metrics_enabled():
            reg = get_registry()
            lbl = {"predictor": self.name}
            reg.inc("alink_serve_batches_total", 1, lbl)
            reg.observe("alink_serve_batch_occupancy", n / bucket, lbl)
        return result

    def predict_row(self, row: Tuple) -> Tuple:
        """LocalPredictor-style single-row serving: the 1-row table trip
        through the bucket-1 program (this is the serial-dispatch
        baseline the micro-batcher is measured against)."""
        one = MTable([row], self._active.mapper.data_schema)
        return self.predict_table(one).row(0)

    # -- parity helpers -------------------------------------------------
    def host_reference(self, data: MTable) -> MTable:
        """The active model applied through the HOST mapper path
        (``map_table``) — the parity baseline of the compiled tier."""
        return self._active.mapper.map_table(data)

    @property
    def output_schema(self):
        return self._active.mapper.get_output_schema()

    @property
    def data_schema(self):
        return self._active.mapper.data_schema
