from .eval_ops import (EvalBinaryClassBatchOp, EvalMultiClassBatchOp,
                       EvalRegressionBatchOp, EvalClusterBatchOp)
