"""ComContext — the per-worker state handle inside a superstep.

Re-design of the reference ``ComContext`` (common/comqueue/ComContext.java:52-65):
there, ``getObj/putObj`` hit a static per-TaskManager heap map keyed by
(handle, taskId). Here the backing store is an explicit functional **carry
pytree** traced through ``lax.while_loop`` (SURVEY §7 "hard parts": every
putObj key becomes a carry entry), plus a read-only dict of device-resident
partitioned/broadcast data (the ``SessionSharedObjs`` cache analogue,
comqueue/SessionSharedObjs.java:157-178).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp


class ComContext:
    AXIS = "d"

    # carry-key prefix of the probe channel (engine + result accessors)
    PROBE_PREFIX = "__probe_"
    # probe series dtype: probes are monitoring scalars, not model state —
    # a fixed narrow dtype keeps the stacked carry small and the series
    # layout independent of the trainer's compute dtype
    PROBE_DTYPE = jnp.float32

    def __init__(self, carry: Dict[str, Any], static: Dict[str, Any],
                 num_workers: int, init_pass: bool,
                 max_iter: int = 0, probes_on: bool = False):
        self._carry = dict(carry)
        self._static = static
        self._num_workers = num_workers
        self._init_pass = init_pass
        self._max_iter = int(max_iter)
        self._probes_on = bool(probes_on) and self._max_iter > 0

    # -- identity --------------------------------------------------------
    @property
    def task_id(self):
        """Worker index along the data mesh axis (Flink getTaskId analogue)."""
        return jax.lax.axis_index(self.AXIS)

    @property
    def num_task(self) -> int:
        return self._num_workers

    @property
    def step_no(self):
        """1-based superstep number (reference ComContext.getStepNo)."""
        return self._carry["__step"]

    @property
    def is_init_step(self) -> bool:
        """True only during the (un-traced-step) first superstep pass.

        Replaces the reference's ``if (context.getStepNo() == 1)`` allocation
        idiom: allocation must happen where the carry structure is being
        built, i.e. the init pass.
        """
        return self._init_pass

    # -- state -----------------------------------------------------------
    def get_obj(self, name: str):
        if name in self._carry:
            v = self._carry[name]
            # collective fusion (ALINK_TPU_FUSE_COLLECTIVES): a deferred
            # reduction stored by a communicate stage materializes on
            # first READ — flushing every independent pending collective
            # as one fused op — so trainer code always receives real
            # traced values, never proxies (jnp coverage of foreign
            # array-likes is partial; see communication._Deferred)
            from .communication import (_Deferred, active_fusion_scope,
                                        resolve_deferred)
            if active_fusion_scope() is not None and any(
                    isinstance(leaf, _Deferred)
                    for leaf in jax.tree_util.tree_leaves(
                        v, is_leaf=lambda x: isinstance(x, _Deferred))):
                self._carry[name] = v = resolve_deferred(v)
            return v
        if name in self._static:
            return self._static[name]
        raise KeyError(f"ComContext: no object '{name}' "
                       f"(carry keys: {sorted(self._carry)}, "
                       f"static keys: {sorted(self._static)})")

    def put_obj(self, name: str, value):
        if name in self._static:
            raise ValueError(f"'{name}' is immutable partitioned/broadcast data")
        self._carry[name] = value

    def contains_obj(self, name: str) -> bool:
        return name in self._carry or name in self._static

    def remove_obj(self, name: str):
        self._carry.pop(name, None)

    # -- health probes (common/health.py) --------------------------------
    @property
    def probes_enabled(self) -> bool:
        """Trace-time truth of the ``ALINK_TPU_HEALTH`` switch. A stage
        may branch on it to skip probe-only arithmetic (the engine folds
        the flag into the program-cache key, so the two variants never
        share a compiled program)."""
        return self._probes_on

    def probe(self, name: str, value) -> None:
        """Publish one named per-superstep health scalar from inside the
        compiled program. The series rides the while-loop carry as a
        stacked ``(max_iter,)`` float32 array prefilled with NaN and
        written at index ``step_no - 1`` — zero host callbacks, no new
        collectives, fetched with the rest of the carry (checkpoint
        snapshots include it, so a resumed run's history stitches).

        No-op when ``ALINK_TPU_HEALTH`` is off — the lowered program is
        then byte-identical to one with no probe calls at all. Call it
        unconditionally from stages; never gate it on your own env read
        (the engine's cache key covers this switch, not yours)."""
        if not self._probes_on:
            return
        key = self.PROBE_PREFIX + name
        v = jnp.asarray(value).astype(self.PROBE_DTYPE).reshape(())
        if key not in self._carry:
            if not self._init_pass:
                raise KeyError(
                    f"probe '{name}' first recorded after the init pass — "
                    f"the carry structure is frozen after superstep 1, so "
                    f"every probe must also be recorded (even with a "
                    f"placeholder value) while ctx.is_init_step is True")
            series = jnp.full((self._max_iter,), jnp.nan, self.PROBE_DTYPE)
        else:
            series = self._carry[key]
        self._carry[key] = jax.lax.dynamic_update_index_in_dim(
            series, v, self.step_no - 1, 0)

    def probe_nonfinite(self, name: str, value) -> None:
        """Probe the count of non-finite elements in a value pytree as
        series ``nonfinite.<name>`` — the NonFiniteRule watchdog input.
        Costs one ``isfinite`` + reduce per leaf inside the program."""
        if not self._probes_on:
            return
        leaves = jax.tree_util.tree_leaves(value)
        cnt = sum((jnp.size(x) - jnp.isfinite(x).sum())
                  if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)
                  else jnp.asarray(0, jnp.int32)
                  for x in leaves)
        self.probe("nonfinite." + name, cnt)

    # -- communication ---------------------------------------------------
    def all_reduce_sum(self, value):
        """Inline psum of a value pytree (communication/AllReduce.java:85-120
        for the common in-stage case; the stage-based ``AllReduce`` class
        remains for queue-structured use)."""
        # late import: communication imports this module at load time
        from .communication import (active_fusion_scope, payload_nbytes,
                                    record_collective)
        scope = active_fusion_scope()
        if scope is not None:
            # deferred (ALINK_TPU_FUSE_COLLECTIVES): back-to-back inline
            # psums (LDA's sstats pairs) coalesce into one collective
            return scope.defer_reduce("sum", value, self.AXIS, "<inline>",
                                      self._num_workers,
                                      kind_label="InlineAllReduce")
        record_collective("InlineAllReduce", "<inline>",
                          payload_nbytes(value), self._num_workers)
        return jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x, self.AXIS), value)

    # -- randomness ------------------------------------------------------
    def rng_key(self):
        """Per-worker, per-step PRNG key (mini-batch SGD sampling etc.)."""
        key = self._carry["__key"]
        return jax.random.fold_in(jax.random.fold_in(key, self.step_no), self.task_id)

    @property
    def carry(self) -> Dict[str, Any]:
        return self._carry
