from .sinks import (BaseSinkStreamOp, CollectSinkStreamOp, CsvSinkStreamOp,
                    DBSinkStreamOp, JdbcRetractSinkStreamOp, LibSvmSinkStreamOp,
                    MySqlSinkStreamOp, TextSinkStreamOp)

__all__ = ["BaseSinkStreamOp", "CollectSinkStreamOp", "CsvSinkStreamOp",
           "DBSinkStreamOp", "JdbcRetractSinkStreamOp", "LibSvmSinkStreamOp",
           "MySqlSinkStreamOp", "TextSinkStreamOp"]
