from .base import (Pipeline, PipelineModel, PipelineStage, Estimator, Transformer,
                   Model, MapModel, Trainer, LocalPredictor)
from . import classification, regression
from .tuning import (ParamGrid, GridSearchCV, GridSearchTVSplit,
                     BinaryClassificationTuningEvaluator,
                     MultiClassClassificationTuningEvaluator,
                     RegressionTuningEvaluator, ClusterTuningEvaluator)
