"""Hypothesis tests + correlation.

Re-design of common/statistics/ ChiSquareTest, Correlation
(Pearson + SpearmanCorrelation.java). chi2 p-values via the regularized
upper incomplete gamma (no scipy in the image).
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import numpy as np


def _gammainc_upper_reg(s: float, x: float) -> float:
    """Q(s, x) = Gamma(s,x)/Gamma(s); series/continued-fraction split."""
    if x < 0 or s <= 0:
        return float("nan")
    if x == 0:
        return 1.0
    if x < s + 1:
        # lower series
        term = 1.0 / s
        total = term
        n = s
        for _ in range(500):
            n += 1
            term *= x / n
            total += term
            if abs(term) < abs(total) * 1e-15:
                break
        p = total * math.exp(-x + s * math.log(x) - math.lgamma(s))
        return max(0.0, 1.0 - p)
    # continued fraction (Lentz)
    tiny = 1e-300
    b = x + 1 - s
    c = 1 / tiny
    d = 1 / b
    h = d
    for i in range(1, 500):
        an = -i * (i - s)
        b += 2
        d = an * d + b
        d = tiny if abs(d) < tiny else d
        c = b + an / c
        c = tiny if abs(c) < tiny else c
        d = 1 / d
        delta = d * c
        h *= delta
        if abs(delta - 1.0) < 1e-15:
            break
    return h * math.exp(-x + s * math.log(x) - math.lgamma(s))


def chi2_sf(x: float, df: int) -> float:
    """P(X > x) for chi-square with df degrees of freedom."""
    return _gammainc_upper_reg(df / 2.0, x / 2.0)


def chi_square_test(col: Sequence, label: Sequence) -> Tuple[float, float, int]:
    """Independence test of a (categorical) column vs the label.

    Returns (chi2, p_value, df). reference: common/statistics/ChiSquareTest.
    """
    xs = [str(v) for v in col]
    ys = [str(v) for v in label]
    xv = sorted(set(xs))
    yv = sorted(set(ys))
    xi = {v: i for i, v in enumerate(xv)}
    yi = {v: i for i, v in enumerate(yv)}
    obs = np.zeros((len(xv), len(yv)))
    for a, b in zip(xs, ys):
        obs[xi[a], yi[b]] += 1
    n = obs.sum()
    exp = np.outer(obs.sum(1), obs.sum(0)) / max(n, 1e-300)
    mask = exp > 0
    chi2 = float(((obs - exp) ** 2 / np.where(mask, exp, 1))[mask].sum())
    df = max((len(xv) - 1) * (len(yv) - 1), 1)
    return chi2, chi2_sf(chi2, df), df


def pearson_corr(X: np.ndarray) -> np.ndarray:
    """Pearson correlation matrix of columns."""
    X = np.asarray(X, np.float64)
    Xc = X - X.mean(0)
    std = Xc.std(0)
    std = np.where(std < 1e-300, 1.0, std)
    C = (Xc / std).T @ (Xc / std) / max(X.shape[0], 1)
    np.fill_diagonal(C, 1.0)
    return np.clip(C, -1.0, 1.0)


def _ranks(v: np.ndarray) -> np.ndarray:
    order = np.argsort(v, kind="mergesort")
    ranks = np.empty(len(v), np.float64)
    sv = v[order]
    uniq, inv, counts = np.unique(sv, return_inverse=True, return_counts=True)
    csum = np.cumsum(counts)
    avg = csum - (counts - 1) / 2.0
    ranks[order] = avg[inv]
    return ranks


def spearman_corr(X: np.ndarray) -> np.ndarray:
    """Spearman rank correlation (reference SpearmanCorrelation.java)."""
    R = np.stack([_ranks(X[:, j]) for j in range(X.shape[1])], axis=1)
    return pearson_corr(R)
