from .params import Params, ParamInfo, WithParams, RangeValidator, InValidator, MinValidator
from .types import AlinkTypes, TableSchema
from .vector import (DenseVector, SparseVector, Vector, VectorUtil, SparseBatch,
                     DenseMatrix)
from .mtable import MTable
from .mlenv import (MLEnvironment, MLEnvironmentFactory, use_local_env,
                    use_remote_env)
from .lazy import LazyEvaluation, LazyObjectsManager
from .health import (HealthAlert, HealthAlertError, HealthMonitor,
                     HealthRule, NonFiniteRule, DivergenceRule, PlateauRule,
                     ThresholdRule, UpdateRatioRule, DriftRule,
                     default_rules, health_enabled)
from .metrics import (MetricsRegistry, get_registry, metrics_enabled,
                      set_registry)
from .profiling import StepTimer, named_stage, trace
from .tracing import (Tracer, get_tracer, set_tracer, tracing_enabled,
                      trace_span, trace_instant)

__all__ = [
    "Params", "ParamInfo", "WithParams", "RangeValidator", "InValidator", "MinValidator",
    "AlinkTypes", "TableSchema", "DenseVector", "SparseVector", "Vector", "VectorUtil",
    "SparseBatch", "DenseMatrix", "MTable", "MLEnvironment", "MLEnvironmentFactory",
    "use_local_env", "use_remote_env", "LazyEvaluation", "LazyObjectsManager",
    "StepTimer", "named_stage", "trace",
    "MetricsRegistry", "get_registry", "set_registry", "metrics_enabled",
    "Tracer", "get_tracer", "set_tracer", "tracing_enabled",
    "trace_span", "trace_instant",
    "HealthAlert", "HealthAlertError", "HealthMonitor", "HealthRule",
    "NonFiniteRule", "DivergenceRule", "PlateauRule", "ThresholdRule",
    "UpdateRatioRule", "DriftRule", "default_rules", "health_enabled",
]
