"""Stream evaluation operators — windowed + cumulative metrics.

Re-design of operator/stream/evaluation/ (BaseEvalClassStreamOp.java:44-87:
``timeWindowAll(timeInterval)`` emits a "window" metrics row and an "all"
(cumulative) metrics row per interval). Here each closed event-time window
emits two rows: (Statistics='window', Data=json) over the window's rows and
(Statistics='all', Data=json) over everything seen so far.
"""

from __future__ import annotations

import json
from typing import List, Optional

import numpy as np

from ....common.mtable import MTable
from ....common.params import ParamInfo, Params
from ....common.types import AlinkTypes, TableSchema
from ....params.shared import (HasLabelCol, HasPositiveLabelValueString,
                               HasPredictionCol, HasPredictionDetailCol)
from ...base import StreamOperator
from ...batch.evaluation.eval_ops import parse_detail_probs
from ...common.evaluation.metrics import (binary_metrics, multiclass_metrics,
                                          regression_metrics)

_OUT_SCHEMA = TableSchema(["Statistics", "Data"],
                          [AlinkTypes.STRING, AlinkTypes.STRING])


class _BaseEvalStreamOp(StreamOperator):
    """Windowed+cumulative metric emission over timed micro-batches."""

    TIME_INTERVAL = ParamInfo("time_interval", float, default=1.0)

    def _metrics_json(self, table: MTable) -> str:  # pragma: no cover
        raise NotImplementedError

    def link_from(self, in_op: StreamOperator) -> "_BaseEvalStreamOp":
        interval = float(self.get_time_interval())
        self._schema = _OUT_SCHEMA

        def emit(window_rows: Optional[MTable], all_rows: Optional[MTable]):
            rows = []
            if window_rows is not None and window_rows.num_rows:
                rows.append(("window", self._metrics_json(window_rows)))
            if all_rows is not None and all_rows.num_rows:
                rows.append(("all", self._metrics_json(all_rows)))
            return MTable(rows, _OUT_SCHEMA) if rows else None

        def gen():
            window: Optional[MTable] = None
            total: Optional[MTable] = None
            window_end = None
            for t, mt in in_op.timed_batches():
                if window_end is None:
                    window_end = (np.floor(t / interval) + 1) * interval
                while t >= window_end:
                    # fire only for windows that saw data (Flink timeWindowAll
                    # does not fire empty windows)
                    if window is not None:
                        out = emit(window, total)
                        if out is not None:
                            yield (window_end, out)
                    window = None
                    window_end += interval
                window = mt if window is None else window.concat_rows(mt)
                total = mt if total is None else total.concat_rows(mt)
            out = emit(window, total)
            if out is not None:
                yield (window_end if window_end is not None else interval, out)

        self._stream_fn = gen
        return self


class EvalBinaryClassStreamOp(_BaseEvalStreamOp, HasLabelCol,
                              HasPredictionDetailCol, HasPositiveLabelValueString):
    """reference: stream/evaluation/EvalBinaryClassStreamOp."""

    def _metrics_json(self, table: MTable) -> str:
        labels = table.col(self.get_label_col())
        details = table.col(self.get_prediction_detail_col() or "pred_detail")
        pos, p_pos = parse_detail_probs(
            details, self.params._m.get("positive_label_value_string"))
        m = binary_metrics(labels, p_pos, pos)
        if len(set(str(l) for l in labels)) < 2:
            # a window that saw one label class still emits the full schema
            # (reference BaseEvalClassStreamOp windows do) — confusion-matrix
            # metrics are well-defined; rank metrics are not, so null them
            d = m.to_dict()
            for k in ("AUC", "KS", "PRC"):
                d[k] = None
            from ...common.evaluation.metrics import BinaryClassMetrics
            return BinaryClassMetrics(d).to_json()
        return m.to_json()


class EvalMultiClassStreamOp(_BaseEvalStreamOp, HasLabelCol, HasPredictionCol,
                             HasPredictionDetailCol):
    """reference: stream/evaluation/EvalMultiClassStreamOp."""

    def _metrics_json(self, table: MTable) -> str:
        labels = table.col(self.get_label_col())
        preds = table.col(self.get_prediction_col())
        return multiclass_metrics(labels, preds).to_json()


class EvalRegressionStreamOp(_BaseEvalStreamOp, HasLabelCol, HasPredictionCol):
    """reference: stream/evaluation/EvalRegressionStreamOp."""

    def _metrics_json(self, table: MTable) -> str:
        y = np.asarray(table.col(self.get_label_col()), np.float64)
        p = np.asarray(table.col(self.get_prediction_col()), np.float64)
        return regression_metrics(y, p).to_json()
