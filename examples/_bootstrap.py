"""Make the repo root importable when an example runs as a script
(``python examples/foo.py`` puts examples/, not the repo root, on
sys.path). Import this before any ``alink_tpu`` import."""

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)
