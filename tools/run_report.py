"""Render an alink_tpu metrics run report (JSONL) as summary tables.

Usage:
    python tools/run_report.py RUN_REPORT.jsonl [--prom] [--all]
                               [--trace TRACE.jsonl]
                               [--health HEALTH.json]

The input is a ``MetricsRegistry.dump()`` file (one JSON object per line;
written by ``registry.dump(path)``, by ``bench.py --metrics-out``, or by
any caller of ``alink_tpu.get_registry()``). Output sections:

  * Run summary      — execs, supersteps, program-cache hit rate;
  * Collectives      — per-collective invocation counts and logical bytes;
  * Host spans       — StepTimer spans (engine phases + user spans);
  * Stream           — per-op micro-batch throughput and latency;
  * Batch operators  — per-op wall time and rows in/out;
  * Everything else  — any counters/gauges/histograms not covered above
    (``--all`` prints the remainder even when a section claimed them).

``--prom`` prints the Prometheus exposition text instead of tables.
``--trace TRACE.jsonl`` appends the span-tracer summary (tools/trace.py)
for a flight-recorder export from the same run, so one report carries
both the aggregates and the timeline rollup. ``--health HEALTH.json``
appends the training-health summary (tools/health.py) for a
``HealthMonitor.save_report()`` file from the same run — aggregates,
timeline, and model health in one report.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Dict, List, Optional, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from alink_tpu.common.metrics import MetricsRegistry  # noqa: E402


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:,.1f} {unit}" if unit != "B" else f"{int(n):,} B"
        n /= 1024.0
    return f"{n:,.1f} TiB"


def _table(headers: List[str], rows: List[List[str]],
           align_right: Optional[List[bool]] = None) -> str:
    if not rows:
        return "  (none)"
    ar = align_right or [False] + [True] * (len(headers) - 1)
    widths = [max(len(str(headers[i])), *(len(str(r[i])) for r in rows))
              for i in range(len(headers))]
    def fmt(cells):
        return "  " + "  ".join(
            str(c).rjust(widths[i]) if ar[i] else str(c).ljust(widths[i])
            for i, c in enumerate(cells)).rstrip()
    sep = "  " + "  ".join("-" * w for w in widths)
    return "\n".join([fmt(headers), sep] + [fmt(r) for r in rows])


def render(reg: MetricsRegistry, show_all: bool = False) -> str:
    snap = reg.snapshot()
    by_name: Dict[str, List[dict]] = {}
    for rec in snap:
        by_name.setdefault(rec["name"], []).append(rec)
    claimed = set()
    out: List[str] = []

    def val(name, labels=None):
        return reg.value(name, labels)

    # -- run summary ------------------------------------------------------
    execs = val("alink_comqueue_execs_total")
    steps = val("alink_comqueue_supersteps_total")
    hits = val("alink_comqueue_program_cache_total", {"result": "hit"})
    miss = val("alink_comqueue_program_cache_total", {"result": "miss"})
    claimed |= {"alink_comqueue_execs_total", "alink_comqueue_supersteps_total",
                "alink_comqueue_program_cache_total"}
    out.append("== Run summary ==")
    rows = [["comqueue execs", f"{int(execs):,}"],
            ["supersteps", f"{int(steps):,}"],
            ["program-cache hits", f"{int(hits):,}"],
            ["program-cache misses", f"{int(miss):,}"]]
    if hits + miss:
        rows.append(["cache hit rate", f"{100.0 * hits / (hits + miss):.1f}%"])
    if execs:
        rows.append(["supersteps / exec", f"{steps / execs:,.1f}"])
    out.append(_table(["metric", "value"], rows))

    # -- collectives ------------------------------------------------------
    out.append("\n== Collectives ==")
    crows = []
    calls = {r["labels"].get("collective", "?"): r["value"]
             for r in by_name.get("alink_collective_calls_total", [])}
    byts = {r["labels"].get("collective", "?"): r["value"]
            for r in by_name.get("alink_collective_logical_bytes_total", [])}
    fused = {r["labels"].get("collective", "?"): r["value"]
             for r in by_name.get("alink_collective_fused_total", [])}
    fbyts = {r["labels"].get("collective", "?"): r["value"]
             for r in by_name.get("alink_collective_payload_fused_bytes", [])}
    claimed |= {"alink_collective_calls_total",
                "alink_collective_logical_bytes_total",
                "alink_collective_fused_total",
                "alink_collective_payload_fused_bytes"}
    for kind in sorted(set(calls) | set(byts)):
        c = calls.get(kind, 0.0)
        b = byts.get(kind, 0.0)
        crows.append([kind, f"{int(c):,}", _fmt_bytes(b),
                      _fmt_bytes(b / c) if c else "-",
                      f"{int(fused.get(kind, 0)):,}",
                      _fmt_bytes(fbyts.get(kind, 0.0))])
    out.append(_table(["collective", "calls", "logical bytes", "bytes/call",
                       "fused calls", "fused bytes"], crows))
    total_fused = sum(fused.values())
    if total_fused:
        out.append(f"  ({int(total_fused):,} collectives were FUSED "
                   f"multi-buffer payloads — ALINK_TPU_FUSE_COLLECTIVES)")

    # -- host spans (StepTimer mirror) ------------------------------------
    out.append("\n== Host spans (StepTimer) ==")
    srows = []
    for rec in by_name.get("alink_step_timer_seconds", []):
        lbl = dict(rec["labels"])
        name = lbl.pop("span", "?")
        extra = ",".join(f"{k}={v}" for k, v in sorted(lbl.items()))
        cnt, total = rec["count"], rec["sum"]
        srows.append([name + (f" [{extra}]" if extra else ""),
                      f"{cnt:,}", f"{total:.3f}",
                      f"{total / cnt:.4f}" if cnt else "-"])
    claimed.add("alink_step_timer_seconds")
    srows.sort(key=lambda r: -float(r[2]))
    out.append(_table(["span", "count", "total_s", "mean_s"], srows))

    # -- stream -----------------------------------------------------------
    out.append("\n== Stream micro-batches ==")
    trows = []
    lat = {}
    for rec in by_name.get("alink_stream_batch_seconds", []):
        lat[rec["labels"].get("op", "?")] = rec
    batches = {r["labels"].get("op", "?"): r["value"]
               for r in by_name.get("alink_stream_batches_total", [])}
    rows_t = {r["labels"].get("op", "?"): r["value"]
              for r in by_name.get("alink_stream_rows_total", [])}
    claimed |= {"alink_stream_batch_seconds", "alink_stream_batches_total",
                "alink_stream_rows_total"}
    for op in sorted(set(lat) | set(batches) | set(rows_t)):
        rec = lat.get(op)
        n = batches.get(op, rec["count"] if rec else 0)
        rw = rows_t.get(op, 0)
        mean = (rec["sum"] / rec["count"]) if rec and rec["count"] else None
        trows.append([op, f"{int(n):,}", f"{int(rw):,}",
                      f"{1e3 * mean:.2f}" if mean is not None else "-",
                      f"{rw / rec['sum']:,.0f}"
                      if rec and rec["sum"] > 0 and rw else "-"])
    out.append(_table(["op", "batches", "rows", "mean ms/batch", "rows/s"],
                      trows))

    ftrl = [(n, by_name[n]) for n in sorted(by_name) if n.startswith("alink_ftrl_")]
    if ftrl:
        out.append("\n== FTRL ==")
        frows = []
        for name, recs in ftrl:
            claimed.add(name)
            for rec in recs:
                lbl = ",".join(f"{k}={v}" for k, v in
                               sorted(rec["labels"].items()))
                if rec["kind"] == "histogram":
                    v = (f"count={rec['count']:,} "
                         f"mean={1e3 * rec['sum'] / rec['count']:.2f}ms"
                         if rec["count"] else "count=0")
                else:
                    v = f"{rec['value']:,.6g}"
                frows.append([name, lbl, v])
        out.append(_table(["metric", "labels", "value"], frows,
                          align_right=[False, False, False]))

    # -- batch operators --------------------------------------------------
    out.append("\n== Batch operators ==")
    brows = []
    op_t = {r["labels"].get("op", "?"): r
            for r in by_name.get("alink_batch_op_seconds", [])}
    op_in = {r["labels"].get("op", "?"): r["value"]
             for r in by_name.get("alink_batch_rows_in_total", [])}
    op_out = {r["labels"].get("op", "?"): r["value"]
              for r in by_name.get("alink_batch_rows_out_total", [])}
    claimed |= {"alink_batch_op_seconds", "alink_batch_rows_in_total",
                "alink_batch_rows_out_total"}
    for op in sorted(set(op_t) | set(op_in) | set(op_out)):
        rec = op_t.get(op)
        cnt = rec["count"] if rec else 0
        total = rec["sum"] if rec else 0.0
        brows.append([op, f"{cnt:,}", f"{total:.3f}",
                      f"{int(op_in.get(op, 0)):,}",
                      f"{int(op_out.get(op, 0)):,}"])
    brows.sort(key=lambda r: -float(r[2]))
    out.append(_table(["op", "links", "total_s", "rows in", "rows out"],
                      brows))

    # -- remainder --------------------------------------------------------
    rest = [n for n in sorted(by_name) if show_all or n not in claimed]
    if rest:
        out.append("\n== Other metrics ==")
        rrows = []
        for name in rest:
            for rec in by_name[name]:
                lbl = ",".join(f"{k}={v}" for k, v in
                               sorted(rec["labels"].items()))
                if rec["kind"] == "histogram":
                    v = (f"count={rec['count']:,} sum={rec['sum']:.4g}"
                         if rec["count"] else "count=0")
                else:
                    v = f"{rec['value']:,.6g}"
                rrows.append([name, rec["kind"], lbl, v])
        out.append(_table(["metric", "kind", "labels", "value"], rrows,
                          align_right=[False, False, False, False]))
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Render an alink_tpu metrics JSONL run report")
    ap.add_argument("report", help="path to a MetricsRegistry.dump() JSONL")
    ap.add_argument("--prom", action="store_true",
                    help="print Prometheus exposition text instead of tables")
    ap.add_argument("--all", action="store_true",
                    help="also list section-claimed metrics under "
                         "'Other metrics'")
    ap.add_argument("--trace", metavar="TRACE",
                    help="append the span-trace summary for a "
                         "Tracer.export_jsonl()/export_chrome() file "
                         "from the same run")
    ap.add_argument("--health", metavar="HEALTH",
                    help="append the training-health summary for a "
                         "HealthMonitor.save_report() JSON from the "
                         "same run")
    args = ap.parse_args(argv)
    if os.path.isdir(args.report):
        # a bench.py --run-dir artifact directory: the metrics dump is
        # the report; sibling trace/health artifacts auto-attach unless
        # explicitly given. The measured profile has its own renderer
        # (tools/doctor.py) — point at it instead of half-rendering.
        d = args.report
        args.report = os.path.join(d, "metrics.jsonl")
        if not os.path.exists(args.report):
            print(f"run_report.py: {d}: no metrics.jsonl inside "
                  f"(not a bench --run-dir directory?)", file=sys.stderr)
            return 1
        for attr, fname in (("trace", "trace.jsonl"),
                            ("health", "health.json")):
            p = os.path.join(d, fname)
            if getattr(args, attr) is None and os.path.exists(p):
                setattr(args, attr, p)
        if not args.prom and os.path.exists(os.path.join(d, "profile.json")):
            print(f"(measured profile present — render it with: "
                  f"python tools/doctor.py --run-dir {d})")
    reg = MetricsRegistry.load(args.report)
    if args.prom:
        sys.stdout.write(reg.render_text())
    else:
        print(render(reg, show_all=args.all))
    if args.trace and not args.prom:
        # never appended in --prom mode: the exposition text on stdout
        # must stay parseable by Prometheus scrapers
        trace_mod = _load_sibling_tool("trace")
        meta, events = trace_mod.load_events(args.trace)
        print()
        print(trace_mod.summarize(meta, events))
    if args.health and not args.prom:
        health_mod = _load_sibling_tool("health")
        from alink_tpu.common.health import HealthMonitor
        print()
        print(health_mod.render(HealthMonitor.load_report(args.health)))
    return 0


def _load_sibling_tool(name: str):
    """Import a sibling tools/*.py module (tools/ is not a package)."""
    import importlib.util
    p = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     f"{name}.py")
    spec = importlib.util.spec_from_file_location(
        f"alink_tpu_tool_{name}", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


if __name__ == "__main__":
    raise SystemExit(main())
