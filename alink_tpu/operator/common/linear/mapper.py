"""LinearModelMapper — batched model serving.

Re-design of common/linear/LinearModelMapper.java (per-row dot product,
reference call stack §3.4) as a batched kernel: the whole input table is
encoded once and scored with one matmul.
"""

from __future__ import annotations

import json
from typing import List, Optional

import numpy as np

from ....common.mtable import MTable
from ....common.types import AlinkTypes, TableSchema
from ....mapper.base import ModelMapper, OutputColsHelper
from ..dataproc.feature_extract import extract_design
from .base import LinearModelData, LinearModelDataConverter, LinearModelType


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-np.clip(x, -500, 500)))


def _serve_chunk() -> int:
    """The serving-kernel scan chunk — the feature axis pads to a
    multiple of it and reduces CHUNK terms per scan step in strict
    left-to-right order. Read from the one canonical definition
    (``serving/sharded.py``; lazy so this module keeps zero import-time
    serving dependencies)."""
    from ....serving.sharded import SERVE_CHUNK
    return SERVE_CHUNK


def _seq_chunk_sum(terms, axis: int):
    """Sum ``terms`` over ``axis`` in a FIXED left-to-right order —
    the canonical serving reduction (``serving/sharded.py
    seq_chunk_sum``): unlike ``jnp.sum`` / ``@``, the float rounding
    cannot depend on the other dimensions' sizes, which is what makes
    serving buckets numerical no-ops. The reduced extent must be a
    multiple of the serve chunk beyond the unroll threshold (encode
    pads it)."""
    from ....serving.sharded import seq_chunk_sum
    return seq_chunk_sum(terms, axis)


class LinearModelMapper(ModelMapper):
    def __init__(self, model_schema, data_schema, params=None, **kwargs):
        super().__init__(model_schema, data_schema, params, **kwargs)
        self.model: Optional[LinearModelData] = None

    def load_model(self, model_table: MTable):
        self.model = LinearModelDataConverter.load_table(model_table)

    # ------------------------------------------------------------------
    def _scores(self, data: MTable) -> np.ndarray:
        m = self.model
        design = extract_design(data, m.feature_names, m.vector_col,
                                np.float64, vector_size=m.vector_size)
        coef = m.coef
        if m.linear_model_type == LinearModelType.Softmax:
            k = len(m.label_values)
            W = coef.reshape(k - 1, -1)
            if m.has_intercept:
                b, Wf = W[:, 0], W[:, 1:]
            else:
                b, Wf = np.zeros(k - 1), W
            Z = _matmul(design, Wf.T, m.vector_size) + b
            return np.concatenate([Z, np.zeros((Z.shape[0], 1))], 1)
        if m.has_intercept:
            b, wf = coef[0], coef[1:]
        else:
            b, wf = 0.0, coef
        return _matmul(design, wf, m.vector_size) + b

    def predict_scores(self, data: MTable) -> np.ndarray:
        return self._scores(data)

    # ------------------------------------------------------------------
    def serving_kernel(self):
        """Compiled-serving contract (serving/predictor.py): host
        encode -> pure jittable score -> host decode via :meth:`_finish`.

        The device kernels accumulate the per-row dot product with a
        chunked ``lax.scan`` over the FEATURE axis (strict left-to-right
        order, elementwise vector ops only), so the reduction order is
        independent of the batch leading dimension — a plain ``X @ w``
        lets XLA pick a shape-dependent tiling, and the same row served
        at bucket 1 vs bucket 128 would round differently in the last
        ulp. This is what makes the serving tier's padding/bucketing a
        bitwise no-op (tests/test_serving.py pins it); against the numpy
        mapper path, labels are exact and scores match to ~1e-15
        relative (BLAS orders its own reduction). The kernel signature
        carries the model GEOMETRY only — weights are program
        arguments, so hot-swapping same-shaped models reuses every
        compiled program."""
        m = self.model
        if m is None:
            raise RuntimeError(
                "load_model must be called before serving_kernel")
        import jax
        from ....serving.predictor import ServingKernel
        ship_dt = np.float64 if jax.config.jax_enable_x64 else np.float32
        softmax = m.linear_model_type == LinearModelType.Softmax
        coef = np.asarray(m.coef, ship_dt)
        if softmax:
            k = len(m.label_values)
            W = coef.reshape(k - 1, -1)
            if m.has_intercept:
                b, Wf = W[:, 0], W[:, 1:]
            else:
                b, Wf = np.zeros(k - 1, ship_dt), W
            model_arrays = (np.ascontiguousarray(Wf),
                            np.ascontiguousarray(b))
            dim = Wf.shape[1]
        else:
            if m.has_intercept:
                b, wf = coef[0], coef[1:]
            else:
                b, wf = np.asarray(0.0, ship_dt), coef
            model_arrays = (np.ascontiguousarray(wf),
                            np.asarray(b, ship_dt))
            dim = wf.shape[0]
        signature = ("linear", str(m.linear_model_type), int(dim),
                     bool(m.has_intercept), bool(softmax),
                     len(m.label_values or ()), str(ship_dt.__name__))

        # feature axis padded to the scan chunk so every program scans
        # whole chunks; the model arrays carry the padding ONCE. The
        # binary/regression kernels pad further, to a whole number of
        # reduction LANES (serving/sharded.py LANE_PAD), so the SAME
        # encode feeds the mesh-sharded program — every lane is then a
        # whole number of chunks on exactly one shard. Zero-padding the
        # tail of a strict left-to-right sum is bitwise-neutral.
        chunk = _serve_chunk()
        if softmax:
            dim8 = -(-dim // chunk) * chunk
        else:
            from ....serving.sharded import LANE_PAD
            dim8 = -(-dim // LANE_PAD) * LANE_PAD

        # Pallas kernel tier (ISSUE 13): resolve the fused-score and
        # low-precision requests ONCE per kernel build. The resolved
        # (dtype, fused) pair rides the SIGNATURE — the serving
        # program-cache key leads with it, so a flag toggle compiles
        # new programs and can never reuse a stale one; every demotion
        # (softmax, backend, probe) is recorded via
        # record_serve_fallback before this returns (False, "f32")
        from ....kernels.serve import (lowp_model_arrays,
                                       make_linear_score_fns,
                                       resolve_serve_kernel)
        fused, sdtype = resolve_serve_kernel(type(self).__name__, dim8,
                                             ship_dt,
                                             supported=not softmax)
        signature = signature + (sdtype, bool(fused))

        def encode(data: MTable, bucket: int):
            design = extract_design(data, m.feature_names, m.vector_col,
                                    ship_dt, vector_size=m.vector_size)
            n = data.num_rows
            if design["kind"] == "dense":
                Xf = design["X"]
                if Xf.shape[1] > dim:
                    raise ValueError(
                        f"request has {Xf.shape[1]} features, model has "
                        f"{dim}")
                X = np.zeros((bucket, dim8), ship_dt)
                X[:n, :Xf.shape[1]] = Xf
                return ("dense", (X,))
            idx0, val0 = design["idx"], design["val"]
            # pad width in steps of the chunk (the FTRL encode
            # convention) so a few compiled widths cover drifting nnz
            w0 = max(idx0.shape[1], 1)
            width = -(-w0 // chunk) * chunk
            idx = np.zeros((bucket, width), np.int32)
            val = np.zeros((bucket, width), ship_dt)
            idx[:n, :idx0.shape[1]] = idx0
            val[:n, :val0.shape[1]] = val0
            return ("sparse", (idx, val))

        if softmax:
            Wf8 = np.zeros((Wf.shape[0], dim8), ship_dt)
            Wf8[:, :dim] = Wf
            model_arrays = (Wf8, model_arrays[1])
        else:
            wf8 = np.zeros(dim8, ship_dt)
            wf8[:dim] = model_arrays[0]
            model_arrays = (wf8, model_arrays[1])

        # version-independent pure functions of (model_arrays, batch):
        # the predictor jit-caches them per (signature, kind, bucket,
        # shapes) and later model versions reuse the compiled program.
        # Every reduction goes through _seq_chunk_sum, never jnp.sum /
        # @ — the bucket-invariance contract.
        if softmax:
            def _dense(mdl, X):
                W, b = mdl     # W (K-1, dim8)
                terms = X[:, :, None] * W.T[None, :, :]   # (n, dim8, K-1)
                return _seq_chunk_sum(terms, axis=1) + b

            def _sparse(mdl, idx, val):
                W, b = mdl
                terms = val[..., None] * W.T[idx]         # (n, w, K-1)
                return _seq_chunk_sum(terms, axis=1) + b
        else:
            def _dense(mdl, X):
                w, b = mdl
                return _seq_chunk_sum(X * w[None, :], axis=1) + b

            def _sparse(mdl, idx, val):
                w, b = mdl
                return _seq_chunk_sum(val * w[idx], axis=1) + b
        device_fns = {"dense": _dense, "sparse": _sparse}
        if fused or sdtype != "f32":
            # the kernel-tier score fns replace the inline ones ONLY
            # when a flag is on: the (off, f32) default executes the
            # statements above verbatim, keeping the flag-off lowered
            # HLO byte-identical to pre-kernel-tier programs
            if sdtype != "f32":
                model_arrays = lowp_model_arrays(model_arrays[0],
                                                 model_arrays[1], sdtype)
            device_fns = make_linear_score_fns(fused, sdtype, ship_dt)

        def decode(outputs, data: MTable) -> MTable:
            scores = np.asarray(outputs[0])
            if softmax:
                scores = np.concatenate(
                    [scores, np.zeros((scores.shape[0], 1), scores.dtype)],
                    axis=1)
            return self._finish(scores, data)

        if softmax or fused or sdtype != "f32":
            # single-device-only kernels: softmax has no sharded twin,
            # and the fused/low-precision tier is single-device too —
            # a sharding request on any of them records the standard
            # no-sharded-kernel fallback (CompiledPredictor) and
            # serves these programs unsharded
            return ServingKernel(signature=signature,
                                 model_arrays=model_arrays,
                                 encode=encode, device_fns=device_fns,
                                 decode=decode)

        # multi-chip serving (ISSUE 11): the weight vector shards over
        # the mesh feature axis 'd' under the io/sharding.py partition
        # rules — the serving-side twin of the FTRL trainer's (z, n)
        # placement — and the sharded score programs cross shards with
        # ONE manifest psum per dispatch (serving/sharded.py).
        from ....serving.sharded import (linear_input_specs,
                                         linear_partition_rules,
                                         make_linear_device_fns,
                                         make_linear_fleet_fns)
        return ServingKernel(signature=signature, model_arrays=model_arrays,
                             encode=encode, device_fns=device_fns,
                             decode=decode, model_names=("w", "b"),
                             partition_rules=linear_partition_rules(),
                             input_specs=linear_input_specs,
                             make_sharded_fns=make_linear_device_fns,
                             make_fleet_fns=make_linear_fleet_fns)

    def get_output_schema(self) -> TableSchema:
        m = self.model
        pred_col = self.params._m.get("prediction_col", "pred")
        detail_col = self.params._m.get("prediction_detail_col")
        reserved = self.params._m.get("reserved_cols")
        regression = m.linear_model_type in LinearModelType.IS_REGRESSION if m else False
        out_type = AlinkTypes.DOUBLE if regression else (m.label_type if m else "STRING")
        cols, types = [pred_col], [out_type]
        if detail_col:
            cols.append(detail_col)
            types.append(AlinkTypes.STRING)
        return OutputColsHelper(self.data_schema, cols, types, reserved).get_output_schema()

    def map_table(self, data: MTable) -> MTable:
        m = self.model
        if m is None:
            raise RuntimeError("load_model must be called before map_table")
        return self._finish(self._scores(data), data)

    def _finish(self, scores: np.ndarray, data: MTable) -> MTable:
        """Scores -> output table (label pick, detail, column merge).

        Split out of :meth:`map_table` so the serving tier
        (``serving/predictor.py``) can decode DEVICE-computed scores
        through the exact same host logic — predictions depend only on
        the scores, whichever path produced them."""
        m = self.model
        pred_col = self.params._m.get("prediction_col", "pred")
        detail_col = self.params._m.get("prediction_detail_col")
        reserved = self.params._m.get("reserved_cols")
        out_cols, out_types = [], []
        details = None
        if m.linear_model_type in LinearModelType.IS_REGRESSION:
            preds = scores
            out_types = [AlinkTypes.DOUBLE]
        elif m.linear_model_type == LinearModelType.Softmax:
            e = np.exp(scores - scores.max(1, keepdims=True))
            probs = e / e.sum(1, keepdims=True)
            pick = probs.argmax(1)
            label_arr = np.empty(len(m.label_values), object)
            label_arr[:] = list(m.label_values)
            preds = _label_array(label_arr[pick])
            if detail_col:
                from ..evaluation.detail import PredictionDetailColumn
                details = PredictionDetailColumn(
                    [str(l) for l in m.label_values], probs)
            out_types = [m.label_type]
        else:
            label_arr = np.empty(2, object)
            label_arr[:] = [m.label_values[0], m.label_values[1]]
            # ~(s > 0), not (s <= 0): a NaN score must keep mapping to the
            # negative label as the per-row 'if s > 0' did
            preds = _label_array(label_arr[(~(scores > 0)).astype(np.intp)])
            if detail_col:
                from ..evaluation.detail import PredictionDetailColumn
                p_pos = _sigmoid(scores)
                details = PredictionDetailColumn(
                    [str(m.label_values[0]), str(m.label_values[1])],
                    np.stack([p_pos, 1.0 - p_pos], axis=1))
            out_types = [m.label_type]
        cols = [pred_col]
        values = [preds]
        if detail_col:
            cols.append(detail_col)
            out_types.append(AlinkTypes.STRING)
            values.append(details if details is not None
                          else np.asarray([None] * len(preds), object))
        helper = OutputColsHelper(data.schema, cols, out_types, reserved)
        return helper.build_output(data, values)


def _matmul(design, w, dim):
    if design["kind"] == "dense":
        return design["X"] @ w
    idx, val = design["idx"], design["val"]
    if w.ndim == 1:
        return (val * w[idx]).sum(-1)
    # (n, nnz, k)
    return (val[..., None] * w[idx]).sum(1)


def _label_array(values: List) -> np.ndarray:
    first = values[0] if len(values) else ""
    if isinstance(first, (int, np.integer)):
        return np.asarray(values, np.int64)
    if isinstance(first, (float, np.floating)):
        return np.asarray(values, np.float64)
    out = np.empty(len(values), object)
    out[:] = values
    return out
