"""Association-rule batch operators.

Re-design of operator/batch/associationrule/FpGrowthBatchOp.java and
PrefixSpanBatchOp.java. Output schemas and separators mirror the
reference exactly (ITEMSETS_COL_NAMES/RULES_COL_NAMES,
FpGrowthBatchOp.java:57-66; PrefixSpanBatchOp.java:40-62): the frequent
patterns are the main output, the rules are side output 0.
"""

from __future__ import annotations

import math
from collections import Counter
from typing import List

from ....common.mtable import MTable
from ....common.params import ParamInfo, RangeValidator
from ....common.types import AlinkTypes, TableSchema
from ...base import BatchOperator
from ...common.associationrule import (extract_rules, fp_growth, prefix_span,
                                       sequence_rules)

ITEM_SEPARATOR = ","
ELEMENT_SEPARATOR = ";"
RULE_SEPARATOR = "=>"


class _AssocParams:
    """params/associationrule/FpGrowthParams.java (shared Has* mixins under
    params/shared/associationrules/)."""
    ITEMS_COL = ParamInfo("items_col", str, "column of item transactions",
                          optional=False)
    MIN_SUPPORT_COUNT = ParamInfo(
        "min_support_count", int,
        "min support as count; -1 means use min_support_percent", default=-1)
    MIN_SUPPORT_PERCENT = ParamInfo(
        "min_support_percent", float, "min support as fraction", default=0.02,
        validator=RangeValidator(0.0, 1.0))
    MIN_CONFIDENCE = ParamInfo("min_confidence", float, "min rule confidence",
                               default=0.05, validator=RangeValidator(0.0, 1.0))
    MAX_PATTERN_LENGTH = ParamInfo("max_pattern_length", int,
                                   "max items per pattern", default=10)


def _min_support(n: int, count: int, percent: float) -> int:
    """FpGrowthBatchOp.getMinSupportCnt semantics."""
    return count if count >= 0 else int(math.floor(n * percent))


class FpGrowthBatchOp(BatchOperator, _AssocParams):
    """reference: operator/batch/associationrule/FpGrowthBatchOp.java"""
    MAX_CONSEQUENT_LENGTH = ParamInfo("max_consequent_length", int,
                                      "max items on rule rhs", default=1)
    MIN_LIFT = ParamInfo("min_lift", float, "min rule lift", default=1.0)

    def link_from(self, in_op: BatchOperator) -> "FpGrowthBatchOp":
        t = in_op.get_output_table()
        col = self.get_items_col()
        raw: List[set] = []
        for v in t.col(col):
            s = str(v).strip() if v is not None else ""
            raw.append({x for x in s.split(ITEM_SEPARATOR) if x} if s else set())
        n = len(raw)
        min_sup = max(_min_support(n, self.get_min_support_count(),
                                   self.get_min_support_percent()), 1)
        # support-ordered int encoding, infrequent items dropped
        # (FpGrowthBatchOp.java qualifiedItems/itemIndex stages)
        counts = Counter(it for s in raw for it in s)
        qualified = sorted((it for it, c in counts.items() if c >= min_sup),
                           key=lambda it: (-counts[it], it))
        index = {it: i for i, it in enumerate(qualified)}
        trans = [[index[it] for it in s if it in index] for s in raw]

        patterns = fp_growth(trans, min_sup, self.get_max_pattern_length())
        item_of = qualified

        def fmt(ids) -> str:
            # lexicographic item order (the reference emits support order,
            # FpGrowthBatchOp.concatItems — sorted here for determinism)
            return ITEM_SEPARATOR.join(sorted(item_of[i] for i in ids))

        pat_rows = sorted(((fmt(p), sup, len(p)) for p, sup in patterns.items()),
                          key=lambda r: (r[2], -r[1], r[0]))
        self.set_output_table(MTable(
            pat_rows, TableSchema(["itemset", "supportcount", "itemcount"],
                                  [AlinkTypes.STRING, AlinkTypes.LONG,
                                   AlinkTypes.LONG])))

        rules = extract_rules(patterns, n, self.get_min_confidence(),
                              self.get_min_lift(),
                              self.get_max_consequent_length())
        rule_rows = sorted(
            ((fmt(a) + RULE_SEPARATOR + fmt(c), len(a) + len(c), lift,
              sup_pct, conf, sup)
             for a, c, sup, lift, sup_pct, conf in rules),
            key=lambda r: (r[1], -r[5], r[0]))
        self._side_outputs = [MTable(
            rule_rows,
            TableSchema(["rule", "itemcount", "lift", "support_percent",
                         "confidence_percent", "transaction_count"],
                        [AlinkTypes.STRING, AlinkTypes.LONG, AlinkTypes.DOUBLE,
                         AlinkTypes.DOUBLE, AlinkTypes.DOUBLE, AlinkTypes.LONG]))]
        return self


class PrefixSpanBatchOp(BatchOperator, _AssocParams):
    """reference: operator/batch/associationrule/PrefixSpanBatchOp.java"""

    def link_from(self, in_op: BatchOperator) -> "PrefixSpanBatchOp":
        t = in_op.get_output_table()
        col = self.get_items_col()
        seqs: List[List[frozenset]] = []
        for v in t.col(col):
            s = str(v).strip() if v is not None else ""
            if not s:
                seqs.append([])
                continue
            seqs.append([frozenset(x for x in e.split(ITEM_SEPARATOR) if x)
                         for e in s.split(ELEMENT_SEPARATOR) if e])
        n = len(seqs)
        min_sup = max(_min_support(n, self.get_min_support_count(),
                                   self.get_min_support_percent()), 1)
        patterns = prefix_span(seqs, min_sup, self.get_max_pattern_length())

        def fmt(pat) -> str:
            return ELEMENT_SEPARATOR.join(
                ITEM_SEPARATOR.join(sorted(e)) for e in pat)

        pat_rows = sorted(
            ((fmt(p), sup, sum(len(e) for e in p)) for p, sup in patterns.items()),
            key=lambda r: (r[2], -r[1], r[0]))
        self.set_output_table(MTable(
            pat_rows, TableSchema(["itemset", "supportcount", "itemcount"],
                                  [AlinkTypes.STRING, AlinkTypes.LONG,
                                   AlinkTypes.LONG])))

        rules = sequence_rules(patterns, n, self.get_min_confidence())
        rule_rows = sorted(
            ((fmt(a) + RULE_SEPARATOR + ITEM_SEPARATOR.join(sorted(c)),
              len(a) + 1, sup_pct, conf, sup)
             for a, c, sup, sup_pct, conf in rules),
            key=lambda r: (r[1], -r[4], r[0]))
        self._side_outputs = [MTable(
            rule_rows,
            TableSchema(["rule", "chain_length", "support", "confidence",
                         "transaction_count"],
                        [AlinkTypes.STRING, AlinkTypes.LONG, AlinkTypes.DOUBLE,
                         AlinkTypes.DOUBLE, AlinkTypes.LONG]))]
        return self
