"""Tracing/profiling subsystem (common/profiling.py) — the TPU build's
answer to the reference's slf4j taskId/stepNo logs + Flink-UI named stages
(SURVEY §5: step-timer, jax.profiler traces, named compiled stages)."""

import os
import time

import numpy as np
import pytest

from alink_tpu.common.profiling import (StepTimer, log_superstep, named_stage,
                                        step_log_enabled, trace)


class TestStepTimer:
    def test_spans_accumulate(self):
        t = StepTimer()
        for _ in range(3):
            with t.span("fit"):
                time.sleep(0.01)
        with t.span("predict"):
            time.sleep(0.01)
        rows = t.report()
        assert [r[0] for r in rows] == ["fit", "predict"]
        name, count, total, mean = rows[0]
        assert count == 3 and total >= 0.03 and abs(mean - total / 3) < 1e-9
        assert "fit" in t.pretty() and "count" in t.pretty()

    def test_span_records_on_exception(self):
        t = StepTimer()
        with pytest.raises(RuntimeError):
            with t.span("boom"):
                raise RuntimeError()
        assert t.report()[0][1] == 1

    def test_reset(self):
        t = StepTimer()
        with t.span("x"):
            pass
        t.reset()
        assert t.report() == [] and "no spans" in t.pretty()


class TestNamedStage:
    def test_names_reach_hlo_metadata(self):
        """Stage names must survive into the compiled program (the Flink-UI
        ``.name()`` analogue) so profiler traces attribute device time."""
        import jax
        import jax.numpy as jnp

        def f(x):
            with named_stage("CalcGradientStage"):
                y = jnp.tanh(x) * 2.0
            return y

        from alink_tpu.common.compat import lowered_text
        txt = lowered_text(jax.jit(f).lower(jnp.ones(8)), debug_info=True)
        assert "CalcGradientStage" in txt

    def test_engine_stages_are_named(self):
        """IterativeComQueue names every stage in the lowered program."""
        import jax
        from alink_tpu.common.mlenv import MLEnvironmentFactory
        from alink_tpu.engine import AllReduce, IterativeComQueue

        env = MLEnvironmentFactory.get_default()

        def my_compute_stage(ctx):
            import jax.numpy as jnp
            if ctx.is_init_step:
                ctx.put_obj("acc", jnp.zeros(4))
            ctx.put_obj("acc", ctx.get_obj("acc") + ctx.get_obj("xs").sum(0))

        q = (IterativeComQueue(env=env, max_iter=3)
             .init_with_partitioned_data("xs", np.ones((16, 4), np.float32))
             .add(my_compute_stage)
             .add(AllReduce("acc")))
        res = q.exec()
        assert res.get("acc").shape == (4,)


class TestTraceAndStepLog:
    def test_trace_writes_profile(self, tmp_path):
        import jax
        import jax.numpy as jnp
        with trace(str(tmp_path)):
            jax.block_until_ready(jnp.arange(16) * 2)
        found = [p for p, _, files in os.walk(tmp_path) for f in files
                 if f.endswith((".xplane.pb", ".json.gz"))]
        assert found, "profiler trace produced no files"

    def test_step_log_gate(self, monkeypatch):
        monkeypatch.delenv("ALINK_TPU_STEP_LOG", raising=False)
        assert not step_log_enabled()
        log_superstep(1)  # no-op without jax.debug machinery engaged
        monkeypatch.setenv("ALINK_TPU_STEP_LOG", "1")
        assert step_log_enabled()

    def test_step_log_emits(self, monkeypatch, capfd):
        import jax
        import jax.numpy as jnp
        monkeypatch.setenv("ALINK_TPU_STEP_LOG", "1")

        @jax.jit
        def f(s):
            log_superstep(s, loss=jnp.float32(0.5))
            return s + 1

        jax.block_until_ready(f(jnp.int32(7)))
        jax.effects_barrier()
        out = capfd.readouterr().out
        assert "superstep 7" in out and "loss=0.5" in out
