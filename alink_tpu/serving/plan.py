"""ServingPlan — ONE hashable key object for the serving tier (ISSUE 17,
the first slice of ROADMAP item 1's ExecutionPlan refactor).

PRs 10-13 each threaded another dimension through the serving program
cache by hand: the kernel signature (geometry + resolved dtype + fused
mode), the encoding kind, the shape bucket, the bucket SET, the
sharded-vs-single-device mode and the mesh fingerprint all rode ad-hoc
tuples assembled inside ``CompiledPredictor._program``, and the fleet
registry (``serving/fleet.py``) would have needed a fourth copy of the
same convention. :class:`ServingPlan` collapses them:

* ``CompiledPredictor`` resolves its plan ONCE at construction and
  derives every program-cache key from :meth:`ServingPlan.program_key`;
* ``ModelRegistry`` keys tenant geometry groups on
  :meth:`ServingPlan.geometry_key` — two tenants share one compiled
  bucket program exactly when their plans are equal (weights are
  program ARGUMENTS, the PR-10 contract);
* swap/snapshot signatures derive from :meth:`ServingPlan.
  swap_signature` — a JSON-stable string, so the fleet's snapshot-store
  re-admission can refuse a snapshot whose serving geometry drifted
  (the ``common/checkpoint.py`` ``meta["signature"]`` contract).

The plan is a FROZEN dataclass of already-resolved values — it never
reads flags or the environment itself (alink-lint's ENV-KEY-FOLD rule
keeps checking the resolution sites: ``CompiledPredictor.__init__``,
the kernel builders, the fleet registry). Everything that can change a
compiled serving program is IN the plan or in the per-dispatch key
dimensions (``kind``, ``bucket``, encoded trailing shapes) it is
combined with.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Tuple

__all__ = ["ServingPlan"]


@dataclass(frozen=True)
class ServingPlan:
    """The resolved serving-program identity.

    ``signature`` — the :class:`~alink_tpu.serving.predictor.
    ServingKernel` signature: model geometry, label arity, resolved
    serve dtype and fused mode (the kernel builder folds
    ``ALINK_TPU_SERVE_DTYPE``/``_FUSED`` into it).
    ``buckets``   — the resolved shape-bucket set; the per-dispatch
    bucket is a separate ``program_key`` dimension, the SET rides the
    plan so two predictors with different bucket grids never alias.
    ``sharded``   — the resolved multi-chip mode (a request for
    sharding that the kernel cannot satisfy resolves to ``False``).
    ``mesh_fp``   — the serving mesh fingerprint (device ids + axis
    names) when sharded; ``None`` single-device.
    """

    signature: Tuple
    buckets: Tuple[int, ...]
    sharded: bool = False
    mesh_fp: Optional[Tuple] = None

    def __post_init__(self):
        object.__setattr__(self, "buckets", tuple(self.buckets))
        if self.mesh_fp is not None:
            object.__setattr__(self, "mesh_fp", tuple(self.mesh_fp))

    # -- derived keys ---------------------------------------------------
    def geometry_key(self) -> Tuple:
        """The tenant-grouping key (``ModelRegistry``): everything that
        decides whether two models can share compiled bucket programs —
        kernel signature (model geometry x encoding dtype x fused mode)
        x bucket set x sharded mode x mesh identity."""
        return (self.signature, self.buckets, bool(self.sharded),
                self.mesh_fp)

    def program_key(self, kind: str, bucket: int,
                    trailing_shapes: Tuple, *,
                    signature: Optional[Tuple] = None,
                    sharded: Optional[bool] = None,
                    lanes: Optional[int] = None) -> Tuple:
        """One compiled program's cache key.

        ``signature``/``sharded`` override the plan's own values for a
        HOT-SWAPPED model version whose kernel differs from the
        construction-time one (a different geometry swapped in compiles
        its own programs; a kernel that cannot shard serves
        single-device) — the per-version truth must ride the key, the
        plan carries the predictor-level resolution. ``lanes`` is the
        fleet's coalesced weight-lane bucket (``None`` = the
        single-model program)."""
        sig = self.signature if signature is None else signature
        sh = self.sharded if sharded is None else bool(sharded)
        # mesh identity stays the LAST element (pinned by
        # tests/test_serving_sharded.py's key introspection)
        return (sig, str(kind), int(bucket), tuple(trailing_shapes),
                self.buckets, None if lanes is None else int(lanes),
                self.mesh_fp if sh else None)

    def swap_signature(self) -> str:
        """JSON-stable geometry identity for swap/snapshot validation:
        the fleet's snapshot store records it as ``meta["signature"]``
        and re-admission refuses a snapshot whose serving geometry no
        longer matches (``common/checkpoint.py`` semantics)."""
        return repr(self.geometry_key())

    def with_signature(self, signature: Tuple) -> "ServingPlan":
        """The same plan serving a different kernel geometry (the
        hot-swap path: buckets/mesh stay, the model signature moves)."""
        return replace(self, signature=tuple(signature))
