"""ComContext — the per-worker state handle inside a superstep.

Re-design of the reference ``ComContext`` (common/comqueue/ComContext.java:52-65):
there, ``getObj/putObj`` hit a static per-TaskManager heap map keyed by
(handle, taskId). Here the backing store is an explicit functional **carry
pytree** traced through ``lax.while_loop`` (SURVEY §7 "hard parts": every
putObj key becomes a carry entry), plus a read-only dict of device-resident
partitioned/broadcast data (the ``SessionSharedObjs`` cache analogue,
comqueue/SessionSharedObjs.java:157-178).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp


class ComContext:
    AXIS = "d"

    def __init__(self, carry: Dict[str, Any], static: Dict[str, Any],
                 num_workers: int, init_pass: bool):
        self._carry = dict(carry)
        self._static = static
        self._num_workers = num_workers
        self._init_pass = init_pass

    # -- identity --------------------------------------------------------
    @property
    def task_id(self):
        """Worker index along the data mesh axis (Flink getTaskId analogue)."""
        return jax.lax.axis_index(self.AXIS)

    @property
    def num_task(self) -> int:
        return self._num_workers

    @property
    def step_no(self):
        """1-based superstep number (reference ComContext.getStepNo)."""
        return self._carry["__step"]

    @property
    def is_init_step(self) -> bool:
        """True only during the (un-traced-step) first superstep pass.

        Replaces the reference's ``if (context.getStepNo() == 1)`` allocation
        idiom: allocation must happen where the carry structure is being
        built, i.e. the init pass.
        """
        return self._init_pass

    # -- state -----------------------------------------------------------
    def get_obj(self, name: str):
        if name in self._carry:
            return self._carry[name]
        if name in self._static:
            return self._static[name]
        raise KeyError(f"ComContext: no object '{name}' "
                       f"(carry keys: {sorted(self._carry)}, "
                       f"static keys: {sorted(self._static)})")

    def put_obj(self, name: str, value):
        if name in self._static:
            raise ValueError(f"'{name}' is immutable partitioned/broadcast data")
        self._carry[name] = value

    def contains_obj(self, name: str) -> bool:
        return name in self._carry or name in self._static

    def remove_obj(self, name: str):
        self._carry.pop(name, None)

    # -- communication ---------------------------------------------------
    def all_reduce_sum(self, value):
        """Inline psum of a value pytree (communication/AllReduce.java:85-120
        for the common in-stage case; the stage-based ``AllReduce`` class
        remains for queue-structured use)."""
        # late import: communication imports this module at load time
        from .communication import payload_nbytes, record_collective
        record_collective("InlineAllReduce", "<inline>",
                          payload_nbytes(value), self._num_workers)
        return jax.tree_util.tree_map(
            lambda x: jax.lax.psum(x, self.AXIS), value)

    # -- randomness ------------------------------------------------------
    def rng_key(self):
        """Per-worker, per-step PRNG key (mini-batch SGD sampling etc.)."""
        key = self._carry["__key"]
        return jax.random.fold_in(jax.random.fold_in(key, self.step_no), self.task_id)

    @property
    def carry(self) -> Dict[str, Any]:
        return self._carry
