"""Stream operator layer (reference operator/stream/ — 14 categories).

The DataStream substrate is the timed micro-batch runtime in ``core.py``;
see ``alink_tpu.operator.base.StreamOperator``.
"""

from .core import BaseStreamTransformOp, FnStreamOp
from .dataproc import (AppendIdStreamOp, FirstNStreamOp,
                       NumericalTypeCastStreamOp, SampleStreamOp,
                       ShuffleStreamOp, SplitStreamOp)
from .evaluation import (EvalBinaryClassStreamOp, EvalMultiClassStreamOp,
                         EvalRegressionStreamOp)
from .nlp import (NGramStreamOp, RegexTokenizerStreamOp, SegmentStreamOp,
                  StopWordsRemoverStreamOp, TokenizerStreamOp)
from .onlinelearning import FtrlPredictStreamOp, FtrlTrainStreamOp
from .predict_ops import *  # noqa: F401,F403 — the *PredictStreamOp family
from .predict_ops import __all__ as _predict_all
from .batch_twins import *  # noqa: F401,F403 — stateless batch-twin stream ops
from .batch_twins import __all__ as _twin_all
from .recommendation import AlsPredictStreamOp
from .sink import (BaseSinkStreamOp, CheckpointSinkStreamOp,
                   CollectSinkStreamOp, CsvSinkStreamOp,
                   DBSinkStreamOp, JdbcRetractSinkStreamOp, LibSvmSinkStreamOp,
                   MySqlSinkStreamOp, TextSinkStreamOp)
from .source import (BaseSourceStreamOp, CsvSourceStreamOp, DBSourceStreamOp,
                     LibSvmSourceStreamOp, MemSourceStreamOp,
                     MySqlSourceStreamOp, NumSeqSourceStreamOp,
                     RandomTableSourceStreamOp, TableSourceStreamOp,
                     TextSourceStreamOp)
from .sql import (AsStreamOp, BaseSqlApiStreamOp, FilterStreamOp,
                  SelectStreamOp, UnionAllStreamOp, WhereStreamOp,
                  WindowGroupByStreamOp)
from .utils import (FlatMapStreamOp, MapperStreamOp, MapStreamOp,
                    ModelMapStreamOp, PrintStreamOp, UDFStreamOp, UDTFStreamOp)

__all__ = [
    "BaseStreamTransformOp", "FnStreamOp",
    "AppendIdStreamOp", "FirstNStreamOp", "NumericalTypeCastStreamOp",
    "SampleStreamOp", "ShuffleStreamOp", "SplitStreamOp",
    "EvalBinaryClassStreamOp", "EvalMultiClassStreamOp", "EvalRegressionStreamOp",
    "FtrlTrainStreamOp", "FtrlPredictStreamOp",
    "NGramStreamOp", "RegexTokenizerStreamOp", "SegmentStreamOp",
    "StopWordsRemoverStreamOp", "TokenizerStreamOp",
    "BaseSinkStreamOp", "CheckpointSinkStreamOp", "CollectSinkStreamOp",
    "CsvSinkStreamOp",
    "DBSinkStreamOp", "JdbcRetractSinkStreamOp", "LibSvmSinkStreamOp",
    "MySqlSinkStreamOp", "TextSinkStreamOp",
    "BaseSourceStreamOp", "CsvSourceStreamOp", "DBSourceStreamOp",
    "LibSvmSourceStreamOp", "MemSourceStreamOp", "MySqlSourceStreamOp",
    "NumSeqSourceStreamOp", "RandomTableSourceStreamOp", "TableSourceStreamOp",
    "TextSourceStreamOp",
    "AsStreamOp", "BaseSqlApiStreamOp", "FilterStreamOp", "SelectStreamOp",
    "UnionAllStreamOp", "WhereStreamOp", "WindowGroupByStreamOp",
    "FlatMapStreamOp", "MapperStreamOp", "MapStreamOp", "ModelMapStreamOp",
    "PrintStreamOp", "UDFStreamOp", "UDTFStreamOp", "AlsPredictStreamOp",
] + list(_predict_all) + list(_twin_all)
