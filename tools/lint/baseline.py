"""Baseline allowlist for alink-lint.

A true positive that is *intentional* — a documented semantics
decision, a pre-registry collective site, a flag-gated debug callback —
gets an entry here instead of a code change. The contract:

  * every entry MUST carry a non-empty ``justification`` string: the
    baseline is a list of explained exceptions, not a mute button;
  * entries match findings by ``(rule, file, ident)`` where ``ident``
    supports ``fnmatch`` globs (``"shard_fn:psum"``, ``"*:psum"``), so
    they survive reformatting — line numbers never appear;
  * ``--strict`` fails on entries that matched NOTHING: the allowlist
    can only shrink with the code, never silently outlive it.

Workflow for an intentional exception (docs/performance.md "alink-lint"):

  1. run ``python -m tools.lint`` and copy the finding's
     ``file`` / ``ident`` pair;
  2. add ``{"rule": ..., "file": ..., "ident": ..., "justification":
     "<why this is safe, with the test/doc that proves it>"}`` to
     ``tools/lint_baseline.json``;
  3. re-run with ``--strict`` — it must exit 0 with your entry consumed
     (listed under ``baselined``) and no stale entries.
"""

from __future__ import annotations

import fnmatch
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .analyzer import Finding, repo_root


class BaselineError(ValueError):
    """A malformed baseline file (missing fields, empty justification)."""


@dataclass
class BaselineEntry:
    rule: str
    file: str
    ident: str
    justification: str
    hits: int = 0

    def matches(self, f: Finding) -> bool:
        return (f.rule == self.rule and f.file == self.file
                and fnmatch.fnmatchcase(f.ident, self.ident))


@dataclass
class Baseline:
    path: str
    entries: List[BaselineEntry] = field(default_factory=list)

    def split(self, findings: Sequence[Finding]
              ) -> Tuple[List[Finding], List[Finding], List[BaselineEntry]]:
        """(violations, baselined, stale_entries)."""
        violations: List[Finding] = []
        baselined: List[Finding] = []
        for f in findings:
            hit = next((e for e in self.entries if e.matches(f)), None)
            if hit is None:
                violations.append(f)
            else:
                hit.hits += 1
                baselined.append(f)
        stale = [e for e in self.entries if e.hits == 0]
        return violations, baselined, stale


def load_baseline(path: Optional[str] = None) -> Baseline:
    if path is None:
        path = os.path.join(repo_root(), "tools", "lint_baseline.json")
    if not os.path.exists(path):
        return Baseline(path=path)
    with open(path) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise BaselineError(f"{path}: not valid JSON ({e})") from e
    if not isinstance(doc, dict) or not isinstance(doc.get("entries", []), list):
        raise BaselineError(
            f"{path}: expected an object with an \"entries\" list")
    entries: List[BaselineEntry] = []
    for i, raw in enumerate(doc.get("entries", [])):
        missing = [k for k in ("rule", "file", "ident", "justification")
                   if not raw.get(k)]
        if missing:
            raise BaselineError(
                f"{path}: entry #{i} is missing/empty {missing} — every "
                f"baseline entry needs rule, file, ident and a non-empty "
                f"justification")
        if len(str(raw["justification"]).strip()) < 20:
            raise BaselineError(
                f"{path}: entry #{i} ({raw['rule']} {raw['ident']}): the "
                f"justification must actually explain WHY the exception "
                f"is safe (got {raw['justification']!r})")
        entries.append(BaselineEntry(rule=raw["rule"], file=raw["file"],
                                     ident=raw["ident"],
                                     justification=raw["justification"]))
    return Baseline(path=path, entries=entries)
