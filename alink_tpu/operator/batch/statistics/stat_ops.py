"""Statistics batch operators.

Re-design of operator/batch/statistics/ (SummarizerBatchOp,
VectorSummarizerBatchOp, CorrelationBatchOp, VectorCorrelationBatchOp,
ChiSquareTestBatchOp + the collectStatistics path, BatchOperator.java:576-603).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ....common.mtable import MTable
from ....common.params import InValidator, ParamInfo
from ....common.types import AlinkTypes, TableSchema
from ....params.shared import (HasLabelCol, HasSelectedCol, HasSelectedCols,
                               HasVectorCol)
from ...base import BatchOperator
from ...common.statistics.hypothesis import (chi_square_test, pearson_corr,
                                             spearman_corr)
from ...common.statistics.summarizer import (TableSummary, summarize_table,
                                             summarize_vector_col)


class SummarizerBatchOp(BatchOperator, HasSelectedCols):
    """reference: SummarizerBatchOp → TableSummary."""

    def __init__(self, params=None, **kwargs):
        super().__init__(params, **kwargs)
        self._summary: Optional[TableSummary] = None

    def link_from(self, in_op: BatchOperator) -> "SummarizerBatchOp":
        t = in_op.get_output_table()
        self._summary = summarize_table(t, self.get_selected_cols())
        self._output = self._summary.to_mtable()
        return self

    def collect_summary(self) -> TableSummary:
        if self._summary is None:
            raise RuntimeError("link first")
        return self._summary


class VectorSummarizerBatchOp(BatchOperator, HasVectorCol, HasSelectedCol):
    """reference: VectorSummarizerBatchOp."""

    def __init__(self, params=None, **kwargs):
        super().__init__(params, **kwargs)
        self._summary = None

    def link_from(self, in_op: BatchOperator) -> "VectorSummarizerBatchOp":
        t = in_op.get_output_table()
        col = self.params._m.get("vector_col") or self.params._m.get("selected_col")
        self._summary = summarize_vector_col(t, col)
        s = self._summary
        self._output = MTable({
            "id": np.arange(s.vector_size()), "mean": s.mean(),
            "standardDeviation": s.standard_deviation(), "min": s.min(),
            "max": s.max(), "numNonZero": s.num_non_zero().astype(np.float64)})
        return self

    def collect_vector_summary(self):
        if self._summary is None:
            raise RuntimeError("link first")
        return self._summary


class CorrelationBatchOp(BatchOperator, HasSelectedCols):
    """reference: CorrelationBatchOp (PEARSON | SPEARMAN)."""
    METHOD = ParamInfo("method", str, default="PEARSON",
                       validator=InValidator(["PEARSON", "SPEARMAN"]))

    def __init__(self, params=None, **kwargs):
        super().__init__(params, **kwargs)
        self._corr: Optional[np.ndarray] = None

    def link_from(self, in_op: BatchOperator) -> "CorrelationBatchOp":
        t = in_op.get_output_table()
        cols = self.get_selected_cols()
        if not cols:
            cols = [n for n, tp in zip(t.schema.names, t.schema.types)
                    if AlinkTypes.is_numeric(tp)]
        X = t.numeric_block(cols)
        C = (pearson_corr(X) if self.get_method().upper() == "PEARSON"
             else spearman_corr(X))
        self._corr = C
        data = {"colName": cols}
        for j, c in enumerate(cols):
            data[c] = C[:, j]
        self._output = MTable(data)
        return self

    def collect_correlation(self) -> np.ndarray:
        if self._corr is None:
            raise RuntimeError("link first")
        return self._corr


class VectorCorrelationBatchOp(BatchOperator, HasVectorCol):
    METHOD = CorrelationBatchOp.METHOD

    def link_from(self, in_op: BatchOperator) -> "VectorCorrelationBatchOp":
        from ...common.dataproc.feature_extract import extract_dense_matrix
        t = in_op.get_output_table()
        X = extract_dense_matrix(t, None, self.params._m.get("vector_col"))
        C = (pearson_corr(X) if self.get_method().upper() == "PEARSON"
             else spearman_corr(X))
        self._corr = C
        self._output = MTable({f"c{j}": C[:, j] for j in range(C.shape[1])})
        return self

    def collect_correlation(self) -> np.ndarray:
        return self._corr


class ChiSquareTestBatchOp(BatchOperator, HasSelectedCols, HasLabelCol):
    """reference: ChiSquareTestBatchOp — per-column chi2 vs label."""

    def link_from(self, in_op: BatchOperator) -> "ChiSquareTestBatchOp":
        t = in_op.get_output_table()
        label = t.col(self.get_label_col())
        rows = []
        for c in self.get_selected_cols():
            chi2, p, df = chi_square_test(t.col(c), label)
            rows.append((c, p, chi2, float(df)))
        self._output = MTable(rows, TableSchema(
            ["colName", "p", "value", "df"],
            [AlinkTypes.STRING, AlinkTypes.DOUBLE, AlinkTypes.DOUBLE, AlinkTypes.DOUBLE]))
        return self


class VectorChiSquareTestBatchOp(BatchOperator, HasVectorCol, HasSelectedCol,
                                 HasLabelCol):
    """reference: VectorChiSquareTestBatchOp — per-component chi2 of the
    vector column against the label."""

    def link_from(self, in_op: BatchOperator) -> "VectorChiSquareTestBatchOp":
        from ...common.dataproc.feature_extract import extract_dense_matrix
        t = in_op.get_output_table()
        col = self.params._m.get("vector_col") or self.params._m.get("selected_col")
        X = extract_dense_matrix(t, None, col)
        label = t.col(self.get_label_col())
        rows = []
        for j in range(X.shape[1]):
            chi2, p, df = chi_square_test(X[:, j], label)
            rows.append((str(j), p, chi2, float(df)))
        self._output = MTable(rows, TableSchema(
            ["colName", "p", "value", "df"],
            [AlinkTypes.STRING, AlinkTypes.DOUBLE, AlinkTypes.DOUBLE,
             AlinkTypes.DOUBLE]))
        return self
