"""JAX version compatibility shims.

The runtime targets current jax (``jax.shard_map``, ``check_vma``); older
containers ship jax 0.4.x where shard_map still lives in
``jax.experimental.shard_map`` and the replication check is spelled
``check_rep``. Every internal caller goes through :func:`shard_map` here so
the version probe happens exactly once per process.

Import of jax is deferred to first call — ``alink_tpu.common`` must stay
importable without touching a backend (XLA flags latch at backend init).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

__all__ = ["shard_map", "lowered_text", "compiled_cost_analysis",
           "device_get_tree"]

_impl: Optional[tuple] = None  # (callable, check_kwarg_name)


def _resolve() -> tuple:
    global _impl
    if _impl is None:
        try:
            from jax import shard_map as sm  # jax >= 0.6 style
            _impl = (sm, "check_vma")
        except ImportError:
            from jax.experimental.shard_map import shard_map as sm
            _impl = (sm, "check_rep")
    return _impl


def shard_map(f: Callable, *, mesh, in_specs, out_specs,
              check_vma: Optional[bool] = None, **kw) -> Callable:
    """``jax.shard_map`` with the replication-check kwarg translated for
    the installed jax. ``check_vma`` unspecified means False on the legacy
    API (its ``check_rep=True`` default rejects valid collective programs
    the current checker accepts)."""
    sm, check_kw = _resolve()
    if check_vma is None and check_kw == "check_rep":
        check_vma = False
    if check_vma is not None:
        kw[check_kw] = check_vma
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def compiled_cost_analysis(stage: Any) -> Optional[dict]:
    """XLA's static cost model for a ``jax.stages.Lowered`` or
    ``Compiled`` object, normalized across jax versions.

    The underlying ``cost_analysis()`` has returned, depending on
    version, a dict, a one-element **list** of dicts (one per program),
    or raised/been absent entirely (older jaxlibs, some backends). This
    shim always returns either a flat ``{str: float}`` dict — the
    interesting keys are ``"flops"`` and ``"bytes accessed"`` — or
    ``None`` (never an exception), so telemetry callers can attach cost
    data when available and degrade silently when not.

    Caveats (documented in docs/observability.md): the model is *static*
    — a ``while``-loop body is costed once, not per trip, so for the
    engine's superstep programs the figures describe one loop pass;
    non-arithmetic ops (data movement, collectives) may be missing or
    backend-approximate.
    """
    fn = getattr(stage, "cost_analysis", None)
    if fn is None:
        return None
    try:
        ca = fn()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    for k, v in ca.items():
        try:
            out[str(k)] = float(v)
        except (TypeError, ValueError):
            continue
    return out or None


def lowered_text(lowered: Any, debug_info: bool = False) -> str:
    """``Lowered.as_text`` across jax versions. Older signatures lack the
    ``debug_info`` kwarg AND strip location metadata from the default
    text; there the MLIR module's own printer recovers named-scope /
    location info."""
    try:
        return lowered.as_text(debug_info=debug_info)
    except TypeError:
        if debug_info:
            try:
                ir = lowered.compiler_ir()
                return ir.operation.get_asm(enable_debug_info=True)
            except Exception:
                pass
        return lowered.as_text()


def device_get_tree(tree: Any) -> Any:
    """Fetch every leaf of a pytree to host numpy in ONE batched
    ``jax.device_get``: the batched call starts all device->host copies
    asynchronously and blocks once, where per-leaf ``np.asarray``
    serializes a link round trip per leaf (~100 ms each on tunneled
    backends). Host leaves pass through as numpy. The one batched-fetch
    idiom every boundary shares (ComQueueResult reads, snapshot
    persistence) — fix fetch behavior here, not at call sites."""
    import jax
    import numpy as np
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return jax.tree_util.tree_unflatten(
        treedef, [np.asarray(x) for x in jax.device_get(leaves)])
