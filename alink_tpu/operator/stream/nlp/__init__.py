"""NLP stream operators (reference operator/stream/nlp/)."""

from __future__ import annotations

from ....common.params import ParamInfo
from ....params.shared import HasOutputCol, HasSelectedCol
from ...common.nlp.segment import SegmentMapper
from ...common.nlp.text import (NGramMapper, RegexTokenizerMapper,
                                StopWordsRemoverMapper, TokenizerMapper)
from ..utils import MapperStreamOp


class TokenizerStreamOp(MapperStreamOp, HasSelectedCol, HasOutputCol):
    MAPPER_CLS = TokenizerMapper


class RegexTokenizerStreamOp(MapperStreamOp, HasSelectedCol, HasOutputCol):
    MAPPER_CLS = RegexTokenizerMapper
    PATTERN = ParamInfo("pattern", str, default=r"\s+")
    GAPS = ParamInfo("gaps", bool, default=True)
    MIN_TOKEN_LENGTH = ParamInfo("min_token_length", int, default=1)
    TO_LOWER_CASE = ParamInfo("to_lower_case", bool, default=True)


class NGramStreamOp(MapperStreamOp, HasSelectedCol, HasOutputCol):
    MAPPER_CLS = NGramMapper
    N = ParamInfo("n", int, default=2)


class StopWordsRemoverStreamOp(MapperStreamOp, HasSelectedCol, HasOutputCol):
    MAPPER_CLS = StopWordsRemoverMapper
    CASE_SENSITIVE = ParamInfo("case_sensitive", bool, default=False)
    STOP_WORDS = ParamInfo("stop_words", list)


class SegmentStreamOp(MapperStreamOp, HasSelectedCol, HasOutputCol):
    MAPPER_CLS = SegmentMapper
    USER_DEFINED_DICT = ParamInfo("user_defined_dict", list)
