"""Trace-time collective fusion (ALINK_TPU_FUSE_COLLECTIVES) + measured
multi-device mesh plumbing — ISSUE 9.

Covers:
  * deferred-reduction accumulator semantics (single-payload passthrough,
    multi-payload flatten/offset-slice, pmin-on-the-max-lane negation,
    fused-group manifest records);
  * engine integration: compiled all-reduce counts actually DROP
    (Newton 2 -> 1 per superstep, ALS normal equations 3 -> 1 per side,
    FM 2 -> 1) while training results stay bitwise-identical for
    logreg/kmeans/ALS/FTRL; dependency-forced programs (L-BFGS line
    search) provably keep their collectives;
  * flag-off lowered HLO byte-identity + program-cache key fold +
    checkpoint-signature fold;
  * fusion observability: alink_collective_fused_total /
    alink_collective_payload_fused_bytes + manifest membership, surfaced
    in tools/run_report.py;
  * io/sharding partition rules (match_partition_rules / state_sharding /
    device_put_state) and the ALINK_TPU_MESH_DEVICES session flag.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from alink_tpu.common.compat import shard_map
from alink_tpu.common.mlenv import MLEnvironment, MLEnvironmentFactory
from alink_tpu.engine import communication as comm
from alink_tpu.engine.comqueue import clear_program_cache, program_cache_stats
from alink_tpu.engine.recovery import program_signature


def _mesh(n=4):
    return Mesh(np.array(jax.devices()[:n]), ("d",))


def _count_allreduce(hlo: str) -> int:
    return hlo.count("all-reduce(") + hlo.count("all-reduce-start(")


def _count_allgather(hlo: str) -> int:
    return hlo.count("all-gather(") + hlo.count("all-gather-start(")


@pytest.fixture
def fused_env(monkeypatch):
    """Arm the fusion flag for one test and isolate the program cache."""
    monkeypatch.setenv("ALINK_TPU_FUSE_COLLECTIVES", "1")
    clear_program_cache()
    yield
    clear_program_cache()


def _with_flag(monkeypatch, value):
    if value is None:
        monkeypatch.delenv("ALINK_TPU_FUSE_COLLECTIVES", raising=False)
    else:
        monkeypatch.setenv("ALINK_TPU_FUSE_COLLECTIVES", value)
    clear_program_cache()


# ---------------------------------------------------------------------------
# accumulator unit semantics
# ---------------------------------------------------------------------------

class TestDeferredAccumulator:
    def test_two_psums_fuse_to_one_op_bitwise(self):
        mesh = _mesh()

        def unfused(a, b):
            return jax.lax.psum(a, "d"), jax.lax.psum(b, "d")

        def fused(a, b):
            with comm.fusing(True):
                x = comm.manifest_psum(a, "d", name="a", num_workers=4)
                y = comm.manifest_psum(b, "d", name="b", num_workers=4)
                return jnp.asarray(x), jnp.asarray(y)

        specs = dict(mesh=mesh, in_specs=(P("d"), P("d")),
                     out_specs=(P(), P()), check_vma=False)
        f0 = jax.jit(shard_map(unfused, **specs))
        f1 = jax.jit(shard_map(fused, **specs))
        r = np.random.RandomState(0)
        a = r.randn(8, 3).astype(np.float32)
        b = r.randn(8, 5).astype(np.float32)
        for u, v in zip(f0(a, b), f1(a, b)):
            assert (np.asarray(u) == np.asarray(v)).all()
        h0 = f0.lower(a, b).compile().as_text()
        h1 = f1.lower(a, b).compile().as_text()
        assert _count_allreduce(h0) == 2
        assert _count_allreduce(h1) == 1

    def test_single_payload_passthrough_is_plain_psum(self):
        """A 1-member lane lowers the ORIGINAL payload through the raw op
        — same compiled collective set as the eager wrapper."""
        mesh = _mesh()

        def one(a, armed):
            if armed:
                with comm.fusing(True):
                    return jnp.asarray(
                        comm.manifest_psum(a, "d", name="x", num_workers=4))
            return comm.manifest_psum(a, "d", name="x", num_workers=4)

        specs = dict(mesh=mesh, in_specs=(P("d"),), out_specs=P(),
                     check_vma=False)
        a = np.ones((8, 3), np.float32)
        h0 = jax.jit(shard_map(lambda a: one(a, False), **specs)).lower(
            a).compile().as_text()
        h1 = jax.jit(shard_map(lambda a: one(a, True), **specs)).lower(
            a).compile().as_text()
        assert _count_allreduce(h0) == _count_allreduce(h1) == 1

    def test_pmin_rides_max_lane_negated_bitwise(self):
        mesh = _mesh()

        def unfused(a, b):
            return (comm.manifest_pmax(a, "d", name="mx", num_workers=4),
                    comm.manifest_pmin(b, "d", name="mn", num_workers=4))

        def fused(a, b):
            with comm.fusing(True):
                mx = comm.manifest_pmax(a, "d", name="mx", num_workers=4)
                mn = comm.manifest_pmin(b, "d", name="mn", num_workers=4)
                return jnp.asarray(mx), jnp.asarray(mn)

        specs = dict(mesh=mesh, in_specs=(P("d"), P("d")),
                     out_specs=(P(), P()), check_vma=False)
        f0 = jax.jit(shard_map(unfused, **specs))
        f1 = jax.jit(shard_map(fused, **specs))
        r = np.random.RandomState(1)
        a = r.randn(8, 4).astype(np.float64)
        b = r.randn(8, 4).astype(np.float64)
        for u, v in zip(f0(a, b), f1(a, b)):
            assert (np.asarray(u) == np.asarray(v)).all()
        assert _count_allreduce(f1.lower(a, b).compile().as_text()) == 1

    def test_gather_pair_fuses_bitwise(self):
        mesh = _mesh()

        def fused(a, b):
            with comm.fusing(True):
                ga = comm.manifest_all_gather(a, "d", name="ga",
                                              num_workers=4)
                gb = comm.manifest_all_gather(b, "d", name="gb",
                                              num_workers=4)
                return jnp.asarray(ga), jnp.asarray(gb)

        def unfused(a, b):
            return (comm.manifest_all_gather(a, "d", name="ga",
                                             num_workers=4),
                    comm.manifest_all_gather(b, "d", name="gb",
                                             num_workers=4))

        specs = dict(mesh=mesh, in_specs=(P("d"), P("d")),
                     out_specs=(P(), P()), check_vma=False)
        f0 = jax.jit(shard_map(unfused, **specs))
        f1 = jax.jit(shard_map(fused, **specs))
        r = np.random.RandomState(2)
        a = r.randn(8, 3).astype(np.float32)
        b = r.randn(8, 2).astype(np.float32)
        for u, v in zip(f0(a, b), f1(a, b)):
            assert (np.asarray(u) == np.asarray(v)).all()
        assert _count_allgather(f1.lower(a, b).compile().as_text()) == 1
        assert _count_allgather(f0.lower(a, b).compile().as_text()) == 2

    def test_dependent_psums_flush_separately(self):
        """A psum whose input uses an earlier psum's OUTPUT cannot fuse
        with it — the flush-on-use rule is the dependency proof."""
        mesh = _mesh()

        def dep(a):
            with comm.fusing(True):
                s = comm.manifest_psum(a, "d", name="s", num_workers=4)
                s2 = comm.manifest_psum(jnp.asarray(s) * 2, "d", name="s2",
                                        num_workers=4)
                return jnp.asarray(s2)

        f = jax.jit(shard_map(dep, mesh=mesh, in_specs=(P("d"),),
                              out_specs=P(), check_vma=False))
        a = np.ones((8, 3), np.float32)
        assert _count_allreduce(f.lower(a).compile().as_text()) == 2
        # s = psum(ones) = 4 per element; s2 = psum(4 * 2) = 32
        assert (np.asarray(f(a)) == 32.0).all()

    def test_fused_record_carries_membership(self):
        mesh = _mesh()
        manifest = []

        def fn(a, b):
            with comm.collecting(manifest):
                with comm.fusing(True):
                    x = comm.manifest_psum(a, "d", name="glw",
                                           num_workers=4)
                    y = comm.manifest_psum(b, "d", name="H", num_workers=4)
                    return jnp.asarray(x), jnp.asarray(y)

        jax.jit(shard_map(fn, mesh=mesh, in_specs=(P("d"), P("d")),
                          out_specs=(P(), P()), check_vma=False)).lower(
            np.ones((8, 2), np.float32), np.ones((8, 3), np.float32))
        fused = [rec for rec in manifest if len(rec) > 3]
        assert len(fused) == 1
        kind, name, nbytes, members = fused[0]
        assert kind == "AllReduce"
        assert members == ("glw", "H")
        assert "fused(glw+H)" == name
        # per-worker shard bytes (2,2)+(2,3) f32 = 40, x 4 workers logical
        assert nbytes == 40 * 4

    def test_record_manifest_charges_fused_metrics(self):
        from alink_tpu.common.metrics import get_registry
        reg = get_registry()
        base = reg.value("alink_collective_fused_total",
                         {"collective": "AllReduce"})
        comm.record_manifest(
            [("AllReduce", "fused(a+b)", 128, ("a", "b")),
             ("AllReduce", "solo", 64)], times=3)
        assert reg.value("alink_collective_fused_total",
                         {"collective": "AllReduce"}) == base + 3
        assert reg.value("alink_collective_payload_fused_bytes",
                         {"collective": "AllReduce"}) >= 3 * 128


# ---------------------------------------------------------------------------
# engine integration: real trainers fused vs unfused
# ---------------------------------------------------------------------------

def _newton_artifacts(env):
    import alink_tpu.operator.common.optim.optimizers as O
    from alink_tpu.operator.common.optim.objfunc import (LogLossFunc,
                                                         UnaryLossObjFunc)
    r = np.random.RandomState(0)
    n, d = 48, 5
    X = r.randn(n, d)
    y = np.where(X[:, 0] > 0, 1.0, -1.0)
    data = {"X": X, "y": y, "w": np.ones(n)}

    def run():
        obj = UnaryLossObjFunc(LogLossFunc(), d, l2=1e-3)
        return O.optimize(obj, data, O.OptimParams(
            method="Newton", max_iter=3, epsilon=0.0), env)[0]

    def hlo():
        import alink_tpu.engine.comqueue as cq
        cap = {}
        orig = cq.IterativeComQueue.exec

        def spy(q):
            cap["hlo"] = q.lowered().compile().as_text()
            raise _Stop()
        cq.IterativeComQueue.exec = spy
        try:
            run()
        except _Stop:
            pass
        finally:
            cq.IterativeComQueue.exec = orig
        return cap["hlo"]

    return run, hlo


class _Stop(Exception):
    pass


class TestEngineFusion:
    def test_newton_two_to_one_bitwise(self, monkeypatch):
        env = MLEnvironmentFactory.get_default()
        run, hlo = _newton_artifacts(env)
        _with_flag(monkeypatch, None)
        h0, c0 = hlo(), run()
        _with_flag(monkeypatch, "1")
        h1, c1 = hlo(), run()
        # module = init-pass + loop-body copies: 2/superstep -> 1
        assert _count_allreduce(h0) == 4
        assert _count_allreduce(h1) == 2
        assert (np.asarray(c0) == np.asarray(c1)).all()

    def test_lbfgs_line_search_is_dependency_forced(self, monkeypatch):
        """L-BFGS's 2 all-reduces per superstep are separated by real
        data flow (the line-loss psum needs the direction built from the
        psummed gradient): fusion must NOT change the count, and results
        stay bitwise-identical."""
        import alink_tpu.operator.common.optim.optimizers as O
        import alink_tpu.engine.comqueue as cq
        from alink_tpu.operator.common.optim.objfunc import (
            LogLossFunc, UnaryLossObjFunc)
        env = MLEnvironmentFactory.get_default()
        r = np.random.RandomState(0)
        n, d = 48, 4
        data = {"X": r.randn(n, d),
                "y": np.where(r.randn(n) > 0, 1.0, -1.0),
                "w": np.ones(n)}

        def run():
            obj = UnaryLossObjFunc(LogLossFunc(), d, l2=1e-3)
            return O.optimize(obj, data, O.OptimParams(
                method="LBFGS", max_iter=3, epsilon=0.0), env)[0]

        def hlo():
            cap = {}
            orig = cq.IterativeComQueue.exec

            def spy(q):
                cap["hlo"] = q.lowered().compile().as_text()
                raise _Stop()
            cq.IterativeComQueue.exec = spy
            try:
                run()
            except _Stop:
                pass
            finally:
                cq.IterativeComQueue.exec = orig
            return cap["hlo"]

        _with_flag(monkeypatch, None)
        h0, c0 = hlo(), run()
        _with_flag(monkeypatch, "1")
        h1, c1 = hlo(), run()
        assert _count_allreduce(h0) == _count_allreduce(h1) == 4
        assert (np.asarray(c0) == np.asarray(c1)).all()

    def test_als_three_to_one_bitwise(self, monkeypatch):
        from alink_tpu.operator.common.recommendation import als as A
        import alink_tpu.engine.comqueue as cq
        env = MLEnvironmentFactory.get_default()
        r = np.random.RandomState(0)
        users = r.randint(0, 24, 300)
        items = r.randint(0, 16, 300)
        ratings = (r.rand(300) * 5).astype(np.float32)
        params = A.AlsTrainParams(rank=3, num_iter=3, lambda_reg=0.1)

        def run():
            return A.als_train(users, items, ratings, params, env=env)

        def hlo():
            cap = {}
            orig = cq.IterativeComQueue.exec

            def spy(q):
                cap["hlo"] = q.lowered().compile().as_text()
                raise _Stop()
            cq.IterativeComQueue.exec = spy
            try:
                run()
            except _Stop:
                pass
            finally:
                cq.IterativeComQueue.exec = orig
            return cap["hlo"]

        _with_flag(monkeypatch, None)
        h0 = hlo()
        r0 = run()
        _with_flag(monkeypatch, "1")
        h1 = hlo()
        r1 = run()
        n0, n1 = _count_allreduce(h0), _count_allreduce(h1)
        # per superstep: two half-sweeps x (A, b, cnt) + rmse = 7 psums
        # unfused; each half-sweep's normal equations fuse 3 -> 1, the
        # rmse psum is dependency-separated -> 3 (x2 module copies)
        assert n0 == 14, n0
        assert n1 == 6, n1
        assert (np.asarray(r0[0]) == np.asarray(r1[0])).all()
        assert (np.asarray(r0[1]) == np.asarray(r1[1])).all()

    def test_kmeans_and_quantile_bitwise(self, monkeypatch):
        from alink_tpu.operator.common.clustering.kmeans import kmeans_train
        from alink_tpu.operator.common.dataproc.quantile import (
            distributed_quantiles)
        env = MLEnvironmentFactory.get_default()
        r = np.random.RandomState(0)
        Xk = r.randn(64, 3)
        Xq = r.randn(128, 3)
        probs = np.array([0.25, 0.5, 0.75])
        _with_flag(monkeypatch, None)
        k0 = np.asarray(kmeans_train(Xk, k=3, max_iter=4, env=env)[0])
        q0 = distributed_quantiles(Xq, probs, env=env)
        _with_flag(monkeypatch, "1")
        k1 = np.asarray(kmeans_train(Xk, k=3, max_iter=4, env=env)[0])
        q1 = distributed_quantiles(Xq, probs, env=env)
        assert (k0 == k1).all()
        assert (q0 == q1).all()

    def test_ftrl_staleness_step_bitwise_across_flag(self, monkeypatch):
        """FTRL margin psums are dependency-forced singles: the compiled
        step program is byte-identical under the flag, so (z, n) match
        bitwise."""
        from alink_tpu.operator.stream.onlinelearning.ftrl import (
            _ftrl_sparse_staleness_step_factory)
        mesh = Mesh(np.array(jax.devices()), ("d",))
        r = np.random.RandomState(0)
        dim = 64
        idx = r.randint(0, dim, (32, 6)).astype(np.int32)
        val = r.rand(32, 6)
        y = r.randint(0, 2, 32).astype(np.float64)
        z0 = np.zeros(dim)
        n0 = np.zeros(dim)

        def run():
            step = _ftrl_sparse_staleness_step_factory(
                mesh, 0.1, 1.0, 1e-3, 1e-3, 8)
            z, n, m = step(idx, val, y, jnp.asarray(z0), jnp.asarray(n0))
            return np.asarray(z), np.asarray(n), np.asarray(m)

        _with_flag(monkeypatch, None)
        z_a, n_a, m_a = run()
        _with_flag(monkeypatch, "1")
        z_b, n_b, m_b = run()
        assert (z_a == z_b).all() and (n_a == n_b).all() \
            and (m_a == m_b).all()

    def test_flag_off_hlo_byte_identical(self, monkeypatch):
        """Unset vs explicit '0' lower byte-identically (the registry
        falsy contract)."""
        env = MLEnvironmentFactory.get_default()
        _, hlo = _newton_artifacts(env)
        _with_flag(monkeypatch, None)
        h_unset = hlo()
        _with_flag(monkeypatch, "0")
        h_zero = hlo()
        assert h_unset == h_zero

    def test_flag_folds_into_program_cache_key(self, monkeypatch):
        env = MLEnvironmentFactory.get_default()
        run, _ = _newton_artifacts(env)
        _with_flag(monkeypatch, None)
        run()
        before = program_cache_stats()
        monkeypatch.setenv("ALINK_TPU_FUSE_COLLECTIVES", "1")  # NO cache
        run()                                                  # clear here
        after = program_cache_stats()
        assert after["misses"] == before["misses"] + 1, \
            "toggling ALINK_TPU_FUSE_COLLECTIVES must MISS, not serve a " \
            "structurally different cached program"

    def test_flag_folds_into_checkpoint_signature(self):
        kw = dict(num_workers=8, max_iter=4, seed=0,
                  part_sig=(("X", (4, 2), "float64"),), bcast_names=("b",),
                  stages_digest=("s",))
        off = program_signature(**kw)
        on = program_signature(fuse_collectives=True, **kw)
        assert "fuse_collectives" not in off       # old snapshots resume
        assert on["fuse_collectives"] is True
        assert off != on

    def test_fused_metrics_after_engine_exec(self, monkeypatch):
        from alink_tpu.common.metrics import get_registry
        env = MLEnvironmentFactory.get_default()
        run, _ = _newton_artifacts(env)
        reg = get_registry()
        base = reg.value("alink_collective_fused_total",
                         {"collective": "AllReduce"})
        _with_flag(monkeypatch, "1")
        run()
        assert reg.value("alink_collective_fused_total",
                         {"collective": "AllReduce"}) > base

    def test_run_report_renders_fused_column(self):
        from alink_tpu.common.metrics import MetricsRegistry
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "rr_fusion_test", os.path.join(
                os.path.dirname(__file__), "..", "tools", "run_report.py"))
        rr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(rr)
        reg = MetricsRegistry()
        lbl = {"collective": "AllReduce"}
        reg.inc("alink_collective_calls_total", 5, lbl)
        reg.inc("alink_collective_logical_bytes_total", 4096, lbl)
        reg.inc("alink_collective_fused_total", 2, lbl)
        reg.inc("alink_collective_payload_fused_bytes", 1024, lbl)
        text = rr.render(reg)
        assert "fused calls" in text
        assert "2 collectives were FUSED" in text


# ---------------------------------------------------------------------------
# partition rules + mesh flag (measured multi-device plumbing)
# ---------------------------------------------------------------------------

class TestPartitionRules:
    def test_match_rules_by_path(self):
        from alink_tpu.io.sharding import match_partition_rules
        tree = {"z": np.zeros(8), "n": np.zeros(8),
                "coef": np.zeros((4, 2)), "lr": np.float64(0.1)}
        specs = match_partition_rules(
            ((r"^(z|n)$", P("d")),), tree, default=P())
        assert specs["z"] == P("d") and specs["n"] == P("d")
        assert specs["coef"] == P()
        assert specs["lr"] == P()          # scalars never partition

    def test_unmatched_leaf_raises_without_default(self):
        from alink_tpu.io.sharding import match_partition_rules
        with pytest.raises(ValueError, match="no rule matches"):
            match_partition_rules(((r"^z$", P("d")),),
                                  {"mystery": np.zeros(4)})

    def test_nested_paths_join_with_slash(self):
        from alink_tpu.io.sharding import match_partition_rules
        tree = {"emb": {"in": np.zeros((8, 2)), "out": np.zeros((8, 2))}}
        specs = match_partition_rules(
            ((r"^emb/in$", P("d")), (r".*", P())), tree)
        assert specs["emb"]["in"] == P("d")
        assert specs["emb"]["out"] == P()

    def test_device_put_state_places_on_mesh(self):
        from alink_tpu.io.sharding import device_put_state
        from alink_tpu.operator.stream.onlinelearning.ftrl import (
            ftrl_state_rules)
        mesh = Mesh(np.array(jax.devices()), ("d",))
        tree = {"z": np.zeros(16), "n": np.zeros(16)}
        placed = device_put_state(tree, mesh, ftrl_state_rules(),
                                  default=P())
        assert placed["z"].sharding.spec == P("d")
        assert placed["n"].sharding.spec == P("d")
        assert (np.asarray(placed["z"]) == 0).all()


class TestMeshDevicesFlag:
    def test_default_is_all_devices(self, monkeypatch):
        monkeypatch.delenv("ALINK_TPU_MESH_DEVICES", raising=False)
        env = MLEnvironment()
        assert env.num_workers == len(jax.devices())

    def test_flag_caps_device_count(self, monkeypatch):
        monkeypatch.setenv("ALINK_TPU_MESH_DEVICES", "4")
        env = MLEnvironment()
        assert env.num_workers == 4
        assert env.mesh.devices.size == 4

    def test_flag_beyond_available_raises(self, monkeypatch):
        monkeypatch.setenv("ALINK_TPU_MESH_DEVICES", "64")
        with pytest.raises(ValueError, match="ALINK_TPU_MESH_DEVICES"):
            MLEnvironment()

    def test_explicit_devices_bypass_flag(self, monkeypatch):
        monkeypatch.setenv("ALINK_TPU_MESH_DEVICES", "2")
        env = MLEnvironment(devices=jax.devices()[:3], parallelism=3)
        assert env.num_workers == 3
