"""Column type system for host-side tables.

Re-design of the reference's Flink ``TableSchema`` + ``VectorTypes``
(common/VectorTypes.java:15-45 — a bimap of type name <-> TypeInformation).
On TPU, strings/objects never leave the host; only encoded numeric tensors
cross to the device, so the type system is purely a host-side contract.
"""

from __future__ import annotations

import numpy as np


class AlinkTypes:
    DOUBLE = "DOUBLE"
    FLOAT = "FLOAT"
    LONG = "LONG"
    INT = "INT"
    BOOLEAN = "BOOLEAN"
    STRING = "STRING"
    DENSE_VECTOR = "DENSE_VECTOR"
    SPARSE_VECTOR = "SPARSE_VECTOR"
    VECTOR = "VECTOR"
    M_TABLE = "MTABLE"
    TIMESTAMP = "TIMESTAMP"
    ANY = "ANY"

    _NUMERIC = {DOUBLE, FLOAT, LONG, INT, BOOLEAN}
    _NP = {
        DOUBLE: np.float64, FLOAT: np.float32, LONG: np.int64, INT: np.int32,
        BOOLEAN: np.bool_,
    }

    @classmethod
    def is_numeric(cls, t: str) -> bool:
        return t in cls._NUMERIC

    @classmethod
    def is_vector(cls, t: str) -> bool:
        return t in (cls.DENSE_VECTOR, cls.SPARSE_VECTOR, cls.VECTOR)

    @classmethod
    def to_numpy_dtype(cls, t: str):
        return cls._NP.get(t, object)

    @classmethod
    def from_value(cls, v) -> str:
        from .vector import DenseVector, SparseVector
        if isinstance(v, bool) or isinstance(v, np.bool_):
            return cls.BOOLEAN
        if isinstance(v, (int, np.integer)):
            return cls.LONG
        if isinstance(v, (float, np.floating)):
            return cls.DOUBLE
        if isinstance(v, str):
            return cls.STRING
        if isinstance(v, DenseVector):
            return cls.DENSE_VECTOR
        if isinstance(v, SparseVector):
            return cls.SPARSE_VECTOR
        if isinstance(v, np.ndarray) and v.ndim == 1:
            return cls.DENSE_VECTOR
        from .mtable import MTable
        if isinstance(v, MTable):
            return cls.M_TABLE
        return cls.ANY

    @classmethod
    def from_numpy_dtype(cls, dt) -> str:
        dt = np.dtype(dt)
        if dt == np.bool_:
            return cls.BOOLEAN
        if np.issubdtype(dt, np.integer):
            return cls.LONG if dt.itemsize > 4 else cls.INT
        if np.issubdtype(dt, np.floating):
            return cls.DOUBLE if dt.itemsize > 4 else cls.FLOAT
        return cls.STRING if dt.kind in "US" else cls.ANY


class TableSchema:
    """Ordered (name, type) pairs; mirrors Flink TableSchema usage in the reference."""

    def __init__(self, names, types):
        names, types = list(names), list(types)
        if len(names) != len(types):
            raise ValueError("names/types length mismatch")
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names: {names}")
        self.names = names
        self.types = types

    @staticmethod
    def parse(spec: str) -> "TableSchema":
        """Parse "col1 TYPE, col2 TYPE" schema strings (reference CsvUtil.schemaStr)."""
        names, types = [], []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            toks = part.split()
            names.append(toks[0])
            types.append(toks[1].upper() if len(toks) > 1 else AlinkTypes.DOUBLE)
        return TableSchema(names, types)

    def to_spec(self) -> str:
        return ", ".join(f"{n} {t}" for n, t in zip(self.names, self.types))

    def index_of(self, name: str) -> int:
        try:
            return self.names.index(name)
        except ValueError:
            raise KeyError(f"column '{name}' not in schema {self.names}") from None

    def type_of(self, name: str) -> str:
        return self.types[self.index_of(name)]

    def __len__(self):
        return len(self.names)

    def __eq__(self, other):
        return (isinstance(other, TableSchema) and self.names == other.names
                and self.types == other.types)

    def __repr__(self):
        return f"TableSchema({self.to_spec()!r})"

    def copy(self) -> "TableSchema":
        return TableSchema(list(self.names), list(self.types))
