"""MLPC, GMM, BisectingKMeans tests."""

import numpy as np
import pytest

from alink_tpu.operator.batch.source import MemSourceBatchOp
from alink_tpu.operator.batch.classification.mlpc_ops import (
    MultilayerPerceptronTrainBatchOp, MultilayerPerceptronPredictBatchOp)
from alink_tpu.operator.batch.clustering.gmm_bisecting import (
    GmmTrainBatchOp, GmmPredictBatchOp, BisectingKMeansTrainBatchOp,
    BisectingKMeansPredictBatchOp)


def test_mlpc_nonlinear():
    # circles: inner vs outer ring — linear models can't, MLP can
    rng = np.random.RandomState(0)
    n = 400
    r = np.where(rng.rand(n) < 0.5, 0.5, 2.0)
    theta = rng.rand(n) * 2 * np.pi
    X = np.stack([r * np.cos(theta), r * np.sin(theta)], 1) + 0.05 * rng.randn(n, 2)
    y = np.where(r < 1.0, "inner", "outer")
    src = MemSourceBatchOp(list(zip(X[:, 0], X[:, 1], y)),
                           "x DOUBLE, y DOUBLE, label STRING")
    train = MultilayerPerceptronTrainBatchOp(
        feature_cols=["x", "y"], label_col="label", layers=[16, 8],
        max_iter=300, seed=1).link_from(src)
    out = (MultilayerPerceptronPredictBatchOp(prediction_col="pred",
                                              prediction_detail_col="d")
           .link_from(train, src)).collect_mtable()
    acc = np.mean([p == l for p, l in zip(out.col("pred"), out.col("label"))])
    assert acc > 0.95
    losses = np.asarray(train.get_side_output(0).get_output_table().col("loss"))
    assert losses[-1] < losses[0]


def test_gmm_two_blobs():
    rng = np.random.RandomState(1)
    X = np.vstack([rng.randn(150, 2) * 0.5 + [0, 0],
                   rng.randn(150, 2) * [1.5, 0.3] + [5, 2]])
    src = MemSourceBatchOp([tuple(r) for r in X], "a DOUBLE, b DOUBLE")
    train = GmmTrainBatchOp(k=2, feature_cols=["a", "b"], max_iter=100,
                            seed=0).link_from(src)
    out = (GmmPredictBatchOp(prediction_col="cid", prediction_detail_col="d")
           .link_from(train, src)).collect_mtable()
    ids = np.asarray(out.col("cid"))
    assert len(set(ids[:150])) == 1 and len(set(ids[150:])) == 1
    assert ids[0] != ids[150]
    # anisotropic covariance learned
    from alink_tpu.operator.batch.clustering.gmm_bisecting import GmmModelDataConverter
    m = GmmModelDataConverter().load_model(train.get_output_table())
    cid2 = ids[150]
    cov2 = m["covs"][cid2]
    assert cov2[0, 0] > cov2[1, 1] * 4  # elongated along x


def test_bisecting_kmeans():
    rng = np.random.RandomState(2)
    X = np.vstack([rng.randn(60, 2) * 0.3 + c
                   for c in [[0, 0], [4, 4], [0, 6], [8, 0]]])
    src = MemSourceBatchOp([tuple(r) for r in X], "a DOUBLE, b DOUBLE")
    train = BisectingKMeansTrainBatchOp(k=4, feature_cols=["a", "b"]).link_from(src)
    out = (BisectingKMeansPredictBatchOp(prediction_col="cid")
           .link_from(train, src)).collect_mtable()
    ids = np.asarray(out.col("cid"))
    for g in range(4):
        seg = ids[g * 60:(g + 1) * 60]
        assert len(set(seg)) == 1
    assert len(set(ids.tolist())) == 4
