"""The compile ledger — observability Layer 7 (ISSUE 19).

Layers 1-6 watch runtime execution; the compile plane stayed dark:
when a flag flip, bucket change or tenant geometry silently triggered
a recompile storm (or a checkpoint refusal), nothing recorded WHICH
key dimension changed.  This module records every compilation event
with its :class:`~alink_tpu.common.plan.ExecutionPlan` digest, wall
time, trigger site and a structural diff against the previous plan at
that cache, so the ledger answers "why did this recompile" by naming
the changed dimension (``ALINK_TPU_SERVE_DTYPE f32->int8``, ``bucket
128->512``, ``mesh 1->4``).

Instrumented caches (each registers once, then records hits / misses /
evictions): the engine program cache (plain + checkpoint-chunked), the
FTRL step-factory lru family, per-predictor serving caches, the fleet
geometry groups, and the sweep compile groups (which ride the engine
cache; their events carry the sweep site label).

Surfaces:

* metrics — ``alink_compile_total`` / ``alink_compile_seconds``
  (histogram) / ``alink_compile_cache_size`` /
  ``alink_compile_evictions_total``, all labeled ``{cache=...}``, plus
  ``alink_compile_storms_total`` and the ``alink_compile_storm_active``
  gauge the PR-16 burn-rate alerting can page on;
* tracer — one ``compile`` instant per event (``common/tracing.py``);
* ``/compilez`` — the adminz view (``common/adminz.py``): live caches
  with occupancy/hit-rate, the last N events with diffs, cold-start
  attribution and storm state;
* post-mortems — a detected storm freezes one debounced PR-18 bundle
  (``postmortem.maybe_bundle``) carrying the ledger snapshot.

The ledger OBSERVES keys and must never be one: the gating flags
(``ALINK_TPU_COMPILE_LEDGER`` — default on, ``ALINK_TPU_COMPILE_RING``)
are registered key-neutral, everything here is host-side, and the
byte-identity tests pin that compiled HLO and every cache key are
identical with the ledger on or off.

Storm thresholds (documented in docs/observability.md): >=
``STORM_MISSES`` compile events on ONE cache within
``STORM_WINDOW_S`` seconds flags a storm; the verdict names the
dimension that changed most often across the storm's diffs.
"""

from __future__ import annotations

import threading
import time
from collections import Counter, deque
from typing import Any, Dict, List, Optional

from .flags import flag_value
from .plan import ExecutionPlan

__all__ = [
    "ledger_enabled", "ring_capacity", "register_cache", "record_event",
    "record_disk_hit", "record_hit", "record_eviction", "set_cache_size",
    "note_wall", "subsystem_start", "register_stage", "lru_call",
    "compilez_doc", "storms", "reset", "STORM_WINDOW_S", "STORM_MISSES",
]

# recompile-storm detector: N misses on one cache inside W seconds
STORM_WINDOW_S = 60.0
STORM_MISSES = 8


def ledger_enabled() -> bool:
    """``ALINK_TPU_COMPILE_LEDGER`` (default ON): the ledger is pure
    host-side bookkeeping — compiled HLO and every cache key are
    byte-identical either way (pinned by tests/test_plan.py)."""
    return bool(flag_value("ALINK_TPU_COMPILE_LEDGER", True))


def ring_capacity() -> int:
    """``ALINK_TPU_COMPILE_RING``: bound of the host-side event ring."""
    return max(16, int(flag_value("ALINK_TPU_COMPILE_RING", 256)))


# ---------------------------------------------------------------------------
# state (module-level, lock-protected except the hot hit counters)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_seq = [0]
_events: deque = deque(maxlen=256)
# cache name -> {"subsystem", "capacity", "hits", "misses", "evictions",
#                "size", "last_plan", "last_digest", "miss_times",
#                "storms", "storm_active"}
_caches: Dict[str, Dict[str, Any]] = {}
# subsystem -> perf_counter at first activity; and -> seconds-to-first-
# compiled-program once the first miss lands (cold-start attribution)
_t0: Dict[str, float] = {}
_ttfp: Dict[str, float] = {}
_stages: Dict[str, Dict[str, Any]] = {}
_start_unix = time.time()


def reset() -> None:
    """Tests only: drop every ring entry, cache row and attribution."""
    with _lock:
        _events.clear()
        _caches.clear()
        _t0.clear()
        _ttfp.clear()
        _stages.clear()
        _lru_families.clear()
        _seq[0] = 0


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------

def _cache_row(cache: str, subsystem: str = "",
               capacity: Optional[int] = None) -> Dict[str, Any]:
    row = _caches.get(cache)
    if row is None:
        row = _caches[cache] = {
            "subsystem": subsystem or cache.split(".")[0],
            "capacity": capacity, "hits": 0, "misses": 0,
            "disk_hits": 0, "evictions": 0, "size": 0, "last_plan": None,
            "last_digest": None, "miss_times": deque(maxlen=64),
            "storms": 0, "storm_active": False,
        }
    elif capacity is not None and row["capacity"] is None:
        row["capacity"] = capacity
    return row


def register_cache(cache: str, subsystem: str = "",
                   capacity: Optional[int] = None) -> None:
    """Announce a cache before its first event (optional — recording
    auto-registers) so /compilez shows it even while empty."""
    if not ledger_enabled():
        return
    with _lock:
        _cache_row(cache, subsystem, capacity)


def subsystem_start(subsystem: str) -> None:
    """Mark a subsystem's activity start for cold-start attribution
    (time-to-first-program).  First call wins; later calls are free."""
    if not ledger_enabled():
        return
    if subsystem not in _t0:
        with _lock:
            _t0.setdefault(subsystem, time.perf_counter())


def register_stage(subsystem: str, stage: str,
                   plan: ExecutionPlan) -> None:
    """Record a composite's stage identity (the online DAG registers
    its train/serve/eval stages) — surfaced under /compilez "stages"
    so a restart's cold-start report names the stage, not just the
    subsystem."""
    if not ledger_enabled():
        return
    with _lock:
        _stages[f"{subsystem}.{stage}"] = {
            "subsystem": subsystem, "stage": stage,
            "digest": plan.digest(),
            "dims": [[n, _short(v)] for n, v in plan.dims],
        }


def _short(v: Any) -> str:
    s = repr(v)
    return s if len(s) <= 120 else s[:117] + "..."


def record_hit(cache: str) -> None:
    """One cache hit.  Hot path (the serving dispatch loop, the FTRL
    per-batch factory lookup): a GIL-atomic counter bump on the
    already-registered row, no lock, no allocation."""
    if not ledger_enabled():
        return
    row = _caches.get(cache)
    if row is None:
        with _lock:
            row = _cache_row(cache)
    row["hits"] += 1


def record_eviction(cache: str, n: int = 1) -> None:
    if not ledger_enabled() or n <= 0:
        return
    with _lock:
        row = _cache_row(cache)
        row["evictions"] += n
        row["size"] = max(0, row["size"] - n)
    _metrics_inc("alink_compile_evictions_total", n, cache)


def set_cache_size(cache: str, size: int) -> None:
    if not ledger_enabled():
        return
    with _lock:
        _cache_row(cache)["size"] = int(size)


def note_wall(cache: str, wall_s: float) -> None:
    """Attach a measured wall to the most recent event of ``cache``.

    jit compiles LAZILY: the engine's miss event is recorded at
    cache-insert time, but the trace+compile wall is only observable
    around the first dispatch — which reports it here.  The histogram
    sample is deferred to this call, so ``alink_compile_seconds`` never
    double-counts an event."""
    if not ledger_enabled():
        return
    with _lock:
        for ev in reversed(_events):
            if ev["cache"] == cache:
                if ev.get("wall_s") is None:
                    ev["wall_s"] = round(float(wall_s), 6)
                break
    _metrics_observe(wall_s, cache)


def record_event(cache: str, plan: ExecutionPlan, *,
                 wall_s: Optional[float] = None, site: str = "",
                 subsystem: str = "") -> Dict[str, Any]:
    """One compilation (cache-miss) event: digest + diff vs the
    previous plan at this cache + metrics/trace/storm/cold-start
    bookkeeping.  Returns the ledger entry (tests introspect it)."""
    if not ledger_enabled():
        return {}
    now = time.perf_counter()
    digest = plan.digest()
    with _lock:
        row = _cache_row(cache, subsystem)
        diff = plan.diff(row["last_plan"])
        row["last_plan"] = plan
        row["last_digest"] = digest
        row["misses"] += 1
        row["size"] += 1
        row["miss_times"].append(now)
        _seq[0] += 1
        ev = {
            "seq": _seq[0], "t_unix": round(time.time(), 3),
            "kind": "miss",
            "cache": cache, "subsystem": row["subsystem"],
            "site": site, "digest": digest,
            "wall_s": None if wall_s is None else round(float(wall_s), 6),
            "diff": diff,
        }
        ring = _events
        if ring.maxlen != ring_capacity():
            ring = deque(ring, maxlen=ring_capacity())
            globals()["_events"] = ring
        ring.append(ev)
        # cold-start attribution: seconds from the subsystem's first
        # activity to its first compiled program
        sub = row["subsystem"]
        if sub in _t0 and sub not in _ttfp:
            _ttfp[sub] = round(now - _t0[sub], 6)
        storm = _check_storm(row)
    _metrics_event(cache, ev, wall_s)
    _trace_event(cache, ev)
    if storm:
        _on_storm(cache, row)
    return ev


def record_disk_hit(cache: str, plan: ExecutionPlan, *, wall_s: float,
                    site: str = "", subsystem: str = "") -> Dict[str, Any]:
    """One AOT-cache load (ISSUE 20): a program installed from disk
    instead of compiled.  A distinct ``disk-hit`` event kind — vs
    ``miss`` (a compilation) and the counter-only in-memory hits —
    carrying the deserialize wall, so /compilez, doctor and fleetz can
    attribute a warm restart.  Counts toward cold-start attribution
    (the program IS the subsystem's first) but never toward storm
    detection: loading from disk is the cure, not the disease."""
    if not ledger_enabled():
        return {}
    now = time.perf_counter()
    digest = plan.digest()
    with _lock:
        row = _cache_row(cache, subsystem)
        diff = plan.diff(row["last_plan"])
        row["last_plan"] = plan
        row["last_digest"] = digest
        row["disk_hits"] += 1
        row["size"] += 1
        _seq[0] += 1
        ev = {
            "seq": _seq[0], "t_unix": round(time.time(), 3),
            "kind": "disk-hit",
            "cache": cache, "subsystem": row["subsystem"],
            "site": site, "digest": digest,
            "wall_s": round(float(wall_s), 6),
            "diff": diff,
        }
        ring = _events
        if ring.maxlen != ring_capacity():
            ring = deque(ring, maxlen=ring_capacity())
            globals()["_events"] = ring
        ring.append(ev)
        sub = row["subsystem"]
        if sub in _t0 and sub not in _ttfp:
            _ttfp[sub] = round(now - _t0[sub], 6)
    from .metrics import get_registry, metrics_enabled
    if metrics_enabled():
        reg = get_registry()
        reg.inc("alink_compile_disk_hits_total", 1, {"cache": cache})
        reg.observe("alink_aot_deserialize_seconds", float(wall_s),
                    {"cache": cache})
        reg.set_gauge("alink_compile_cache_size", row["size"],
                      {"cache": cache})
    try:
        from .tracing import trace_instant
        trace_instant("compile.disk-hit", cat="compile", args={
            "cache": cache, "site": site, "digest": digest,
            "wall_s": round(float(wall_s), 6),
        })
    except Exception:
        pass
    return ev


def _check_storm(row: Dict[str, Any]) -> bool:
    """Callers hold ``_lock``.  True exactly on the transition into an
    active storm (re-arming only after the window drains)."""
    times = row["miss_times"]
    now = times[-1]
    recent = sum(1 for t in times if now - t <= STORM_WINDOW_S)
    if recent >= STORM_MISSES:
        if not row["storm_active"]:
            row["storm_active"] = True
            row["storms"] += 1
            return True
        return False
    row["storm_active"] = False
    return False


def _dominant_dim(cache: str) -> Optional[Dict[str, Any]]:
    """The dimension that changed most often across this cache's recent
    events — the storm verdict's "name the flag" answer."""
    counts: Counter = Counter()
    sample: Dict[str, Dict[str, str]] = {}
    for ev in _events:
        if ev["cache"] != cache or ev.get("kind") == "disk-hit":
            continue
        for d in ev["diff"]:
            if d["dim"] == "cold-start":
                continue
            counts[d["dim"]] += 1
            sample[d["dim"]] = d
    if not counts:
        return None
    dim, n = counts.most_common(1)[0]
    out = dict(sample[dim])
    out["count"] = n
    return out


def _on_storm(cache: str, row: Dict[str, Any]) -> None:
    from .metrics import get_registry, metrics_enabled
    dom = None
    with _lock:
        dom = _dominant_dim(cache)
    detail = f"{STORM_MISSES}+ compiles on {cache!r} within " \
             f"{STORM_WINDOW_S:.0f}s"
    if dom:
        detail += (f"; dominant changed dimension {dom['dim']} "
                   f"({dom['old']} -> {dom['new']}, x{dom['count']})")
    if metrics_enabled():
        reg = get_registry()
        reg.inc("alink_compile_storms_total", 1, {"cache": cache})
        reg.set_gauge("alink_compile_storm_active", 1, {"cache": cache})
    try:
        from .tracing import trace_instant
        trace_instant("compile.storm", cat="compile",
                      args={"cache": cache, "detail": detail})
    except Exception:
        pass
    try:
        from .postmortem import maybe_bundle
        maybe_bundle("compile_storm", detail=detail,
                     extra={"compilez": compilez_doc()})
    except Exception:
        pass


def _metrics_inc(name: str, n: float, cache: str) -> None:
    from .metrics import get_registry, metrics_enabled
    if metrics_enabled():
        get_registry().inc(name, n, {"cache": cache})


def _metrics_observe(wall_s: float, cache: str) -> None:
    from .metrics import get_registry, metrics_enabled
    if metrics_enabled():
        get_registry().observe("alink_compile_seconds", float(wall_s),
                               {"cache": cache})


def _metrics_event(cache: str, ev: Dict[str, Any],
                   wall_s: Optional[float]) -> None:
    from .metrics import get_registry, metrics_enabled
    if not metrics_enabled():
        return
    reg = get_registry()
    reg.inc("alink_compile_total", 1, {"cache": cache})
    reg.set_gauge("alink_compile_cache_size",
                  _caches[cache]["size"], {"cache": cache})
    if wall_s is not None:
        reg.observe("alink_compile_seconds", float(wall_s),
                    {"cache": cache})
    if not _caches[cache]["storm_active"]:
        reg.set_gauge("alink_compile_storm_active", 0, {"cache": cache})


def _trace_event(cache: str, ev: Dict[str, Any]) -> None:
    try:
        from .tracing import trace_instant
        trace_instant("compile", cat="compile", args={
            "cache": cache, "site": ev["site"], "digest": ev["digest"],
            "changed": ",".join(d["dim"] for d in ev["diff"])[:200],
        })
    except Exception:
        pass


# ---------------------------------------------------------------------------
# lru-factory instrumentation
# ---------------------------------------------------------------------------

def lru_call(cache: str, factory, args: tuple, *, plan: ExecutionPlan,
             site: str, subsystem: str = "", kwargs: Optional[dict] = None):
    """Call a ``functools.lru_cache`` step factory and classify the
    lookup by ``cache_info()`` miss delta — the factories stay exactly
    as they are (lru keys byte-identical; the ledger observes from
    outside).  With the ledger off this is a direct call."""
    kwargs = kwargs or {}
    if not ledger_enabled() or not hasattr(factory, "cache_info"):
        # monkeypatched/plain factories (tests) bypass the ledger
        return factory(*args, **kwargs)
    before = factory.cache_info().misses
    t0 = time.perf_counter()
    out = factory(*args, **kwargs)
    if factory.cache_info().misses > before:
        record_event(cache, plan, wall_s=time.perf_counter() - t0,
                     site=site, subsystem=subsystem)
        set_cache_size(cache, _lru_family_size(cache, factory))
    else:
        record_hit(cache)
    return out


_lru_families: Dict[str, list] = {}


def _lru_family_size(cache: str, factory) -> int:
    """Live entry count across every factory seen under one cache
    label (the 7 FTRL factories aggregate as ``ftrl.step``)."""
    fams = _lru_families.setdefault(cache, [])
    if factory not in fams:
        fams.append(factory)
    return sum(f.cache_info().currsize for f in fams)


# ---------------------------------------------------------------------------
# the /compilez document
# ---------------------------------------------------------------------------

def storms() -> List[str]:
    """Names of caches currently inside an active storm window."""
    with _lock:
        return sorted(c for c, r in _caches.items() if r["storm_active"])


def compilez_doc(n: Optional[int] = None) -> Dict[str, Any]:
    """The /compilez response body (and the doctor/fleetz input): live
    caches with occupancy + hit rate, the last ``n`` events (diffs
    included), cold-start attribution and storm state.  JSON-safe by
    construction."""
    cap = ring_capacity()
    n = cap if n is None else max(1, min(int(n), cap))
    with _lock:
        caches = {}
        for name, r in _caches.items():
            total = r["hits"] + r["misses"]
            caches[name] = {
                "subsystem": r["subsystem"],
                "size": r["size"], "capacity": r["capacity"],
                "hits": r["hits"], "misses": r["misses"],
                "disk_hits": r["disk_hits"],
                "evictions": r["evictions"],
                "hit_rate": round(r["hits"] / total, 4) if total else None,
                "last_digest": r["last_digest"],
                "storm_active": r["storm_active"],
                "storms": r["storms"],
                "dominant_dim": _dominant_dim(name),
            }
        events = list(_events)[-n:]
        doc = {
            "enabled": ledger_enabled(),
            "since_unix": round(_start_unix, 3),
            "ring_capacity": cap,
            "storm_window_s": STORM_WINDOW_S,
            "storm_misses": STORM_MISSES,
            "caches": caches,
            "events": events,
            "cold_start": {
                "started": sorted(_t0),
                "time_to_first_program_s": dict(_ttfp),
            },
            "stages": dict(_stages),
        }
    return doc
