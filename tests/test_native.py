"""Native parser tests: correctness vs the pure-Python paths, graceful
fallback, and the extract_design fast path."""

import os

import numpy as np
import pytest

from alink_tpu.native import (get_lib, parse_libsvm_bytes,
                              parse_numeric_csv_bytes, parse_vector_lines)


def test_native_available():
    # the toolchain is baked into the image; the build must succeed here
    assert get_lib() is not None


def test_libsvm_native_matches_python(tmp_path):
    rng = np.random.RandomState(0)
    lines = []
    for i in range(200):
        k = rng.randint(1, 8)
        idx = np.sort(rng.choice(50, size=k, replace=False)) + 1
        vals = rng.randn(k).round(4)
        body = " ".join(f"{a}:{b}" for a, b in zip(idx, vals))
        lines.append(f"{rng.choice([-1.0, 1.0])} {body}\n")
    p = tmp_path / "data.svm"
    p.write_text("".join(lines))

    from alink_tpu.io.csv import read_libsvm
    fast = read_libsvm(str(p))
    os.environ["ALINK_NO_NATIVE"] = "1"
    try:
        slow = read_libsvm(str(p))
    finally:
        del os.environ["ALINK_NO_NATIVE"]
    np.testing.assert_allclose(np.asarray(fast.col("label"), float),
                               np.asarray(slow.col("label"), float))
    for a, b in zip(fast.col("features"), slow.col("features")):
        np.testing.assert_array_equal(a.indices, b.indices)
        np.testing.assert_allclose(a.values, b.values)


def test_malformed_and_comma_literals():
    # label-token containing ':' is ALL label (count/fill must agree — an
    # earlier disagreement overran the nnz-sized buffers)
    labels, indptr, idx, val = parse_libsvm_bytes(b"1:2 3:4\n")
    assert len(idx) == 1 and indptr.tolist() == [0, 1] and idx[0] == 2
    # comma-separated pairs are valid sparse literals (VectorUtil semantics)
    indptr, idx, val, dim = parse_vector_lines(b"0:1.5,3:2.0\n")
    assert idx.tolist() == [0, 3] and dim == 4


def test_numeric_csv():
    m = parse_numeric_csv_bytes(b"1,2.5,3\n4,,6\n7,8,\n")
    np.testing.assert_allclose(m[0], [1, 2.5, 3])
    assert np.isnan(m[1, 1]) and np.isnan(m[2, 2])
    assert m.shape == (3, 3)


def test_vector_lines_and_fast_path():
    indptr, idx, val, dim = parse_vector_lines(b"$6$0:1.5 3:2.0\n1:7.0\n")
    assert dim == 6
    np.testing.assert_array_equal(indptr, [0, 2, 3])
    np.testing.assert_array_equal(idx, [0, 3, 1])

    # extract_design picks the native path for all-literal columns and it
    # must agree with the per-row parse
    from alink_tpu.common.mtable import MTable
    from alink_tpu.common.types import TableSchema, AlinkTypes
    from alink_tpu.operator.common.dataproc.feature_extract import extract_design
    col = ["$6$0:1.5 3:2.0", "1:7.0", "$6$2:1.0 4:4.0 5:5.0"]
    t = MTable({"v": col}, TableSchema(["v"], [AlinkTypes.STRING]))
    d1 = extract_design(t, None, "v")
    os.environ["ALINK_NO_NATIVE"] = "1"
    try:
        import alink_tpu.native as nat
        d2 = extract_design(t, None, "v")
    finally:
        del os.environ["ALINK_NO_NATIVE"]
    assert d1["kind"] == d2["kind"] == "sparse"
    assert d1["dim"] == d2["dim"] == 6
    # padded layouts may differ in width; compare densified
    from alink_tpu.common.vector import SparseBatch
    X1 = SparseBatch(d1["idx"], d1["val"], d1["dim"]).to_dense(np.float64)
    X2 = SparseBatch(d2["idx"], d2["val"], d2["dim"]).to_dense(np.float64)
    np.testing.assert_allclose(X1, X2)


def test_native_speedup_sanity():
    """Native must beat pure Python on a meaningful batch (soft check)."""
    import time
    rng = np.random.RandomState(1)
    lines = []
    for i in range(20000):
        k = rng.randint(3, 12)
        idx = np.sort(rng.choice(1000, size=k, replace=False))
        body = " ".join(f"{a}:{b:.4f}" for a, b in zip(idx, rng.randn(k)))
        lines.append(f"1 {body}")
    data = ("\n".join(lines) + "\n").encode()

    t0 = time.perf_counter()
    out = parse_libsvm_bytes(data)
    t_native = time.perf_counter() - t0
    assert out is not None and len(out[0]) == 20000

    t0 = time.perf_counter()
    for ln in data.decode().splitlines():
        parts = ln.split()
        float(parts[0])
        for p in parts[1:]:
            a, b = p.split(":")
            int(a), float(b)
    t_py = time.perf_counter() - t0
    # be generous: only assert native isn't slower
    assert t_native < t_py, (t_native, t_py)


class TestMurmurBatch:
    def test_matches_pure_python(self):
        from alink_tpu.native import murmur32_batch
        from alink_tpu.operator.batch.feature.feature_ops import murmur32
        tokens = [b"", b"a", b"ab", b"abc", b"abcd", b"abcde",
                  "col=värde".encode(), b"x" * 1000]
        for seed in (0, 7, 0xDEADBEEF):
            got = murmur32_batch(tokens, seed=seed)
            if got is None:
                import pytest
                pytest.skip("native library unavailable")
            want = [murmur32(t, seed) for t in tokens]
            assert got.tolist() == want

    def test_mod_reduction(self):
        from alink_tpu.native import murmur32_batch
        from alink_tpu.operator.batch.feature.feature_ops import murmur32
        tokens = [f"f={i}".encode() for i in range(500)]
        got = murmur32_batch(tokens, mod=97)
        if got is None:
            import pytest
            pytest.skip("native library unavailable")
        assert got.tolist() == [murmur32(t) % 97 for t in tokens]
        assert (got >= 0).all() and (got < 97).all()

    def test_hasher_native_matches_python(self, monkeypatch):
        """FeatureHasherBatchOp output must be bit-identical with and
        without the native hasher."""
        from alink_tpu.operator.batch.source import MemSourceBatchOp
        from alink_tpu.operator.batch.feature.feature_ops import \
            FeatureHasherBatchOp

        rows = [["u1", 1.5, None], ["u2", None, "x"], [None, -2.0, "y"]]
        def run():
            src = MemSourceBatchOp(rows, "a STRING, b DOUBLE, c STRING")
            out = []
            for fa in (False, True):
                op = FeatureHasherBatchOp(selected_cols=["a", "b", "c"],
                                          num_features=96, field_aware=fa,
                                          output_col="v").link_from(src)
                out.append([r[-1] for r in op.collect()])
            return out

        native = run()
        monkeypatch.setenv("ALINK_NO_NATIVE", "1")
        pure = run()
        assert native == pure


class TestNativeVsPythonDifferential:
    """Differential harness: every native parser must agree with the
    pure-Python fallback on the same bytes (ALINK_NO_NATIVE=1 forces the
    fallback at call time — no cache to clear). Randomized inputs cover
    negatives, exponent notation, blank lines, and CRLF."""

    def _tables(self):
        rng = np.random.RandomState(0)
        for trial in range(6):
            n = rng.randint(1, 40)
            c = rng.randint(1, 6)
            m = rng.randn(n, c) * 10 ** rng.randint(-3, 4)
            if trial % 2:
                m = np.round(m)         # integer-looking values
            yield m

    def test_numeric_csv_differential(self, tmp_path, monkeypatch):
        from alink_tpu.common.types import TableSchema
        from alink_tpu.io.csv import read_csv
        for k, m in enumerate(self._tables()):
            nl = "\r\n" if k % 3 == 0 else "\n"
            txt = nl.join(",".join(f"{v:.10g}" for v in row) for row in m)
            if k % 2 == 0:
                txt += nl               # trailing newline variant
            p = tmp_path / f"t{k}.csv"
            p.write_text(txt)
            schema = TableSchema.parse(
                ", ".join(f"c{j} DOUBLE" for j in range(m.shape[1])))
            fast = read_csv(str(p), schema)
            monkeypatch.setenv("ALINK_NO_NATIVE", "1")
            slow = read_csv(str(p), schema)
            monkeypatch.delenv("ALINK_NO_NATIVE")
            assert fast.num_rows == slow.num_rows == m.shape[0]
            for j in range(m.shape[1]):
                np.testing.assert_allclose(
                    np.asarray(fast.col(f"c{j}"), float),
                    np.asarray(slow.col(f"c{j}"), float), rtol=1e-12)

    def test_libsvm_differential(self, tmp_path, monkeypatch):
        from alink_tpu.io.csv import read_libsvm
        rng = np.random.RandomState(1)
        lines = []
        for i in range(60):
            nnz = rng.randint(0, 6)
            idx = sorted(rng.choice(50, nnz, replace=False) + 1)
            vals = rng.randn(nnz) * 10 ** rng.randint(-2, 3)
            lines.append(" ".join(
                [f"{rng.choice([-1, 1, 0, 2]):g}"]
                + [f"{a}:{v:.8g}" for a, v in zip(idx, vals)]))
        p = tmp_path / "d.svm"
        p.write_text("\n".join(lines) + "\n")
        fast = read_libsvm(str(p), vector_size=64)
        monkeypatch.setenv("ALINK_NO_NATIVE", "1")
        slow = read_libsvm(str(p), vector_size=64)
        monkeypatch.delenv("ALINK_NO_NATIVE")
        assert fast.num_rows == slow.num_rows == 60
        np.testing.assert_allclose(np.asarray(fast.col("label"), float),
                                   np.asarray(slow.col("label"), float))
        for a, b in zip(fast.col("features"), slow.col("features")):
            assert a.size() == b.size()
            np.testing.assert_array_equal(np.asarray(a.indices),
                                          np.asarray(b.indices))
            np.testing.assert_allclose(np.asarray(a.values),
                                       np.asarray(b.values), rtol=1e-12)

    def test_murmur_differential(self, monkeypatch):
        from alink_tpu.operator.batch.feature.feature_ops import murmur32_cells
        toks = [f"field_{i}={chr(65 + i % 26) * (i % 7 + 1)}".encode()
                for i in range(300)] + ["".encode(), "北京".encode() * 3]
        fast = murmur32_cells(toks, seed=17, mod=1024)
        monkeypatch.setenv("ALINK_NO_NATIVE", "1")
        slow = murmur32_cells(toks, seed=17, mod=1024)
        monkeypatch.delenv("ALINK_NO_NATIVE")
        np.testing.assert_array_equal(np.asarray(fast), np.asarray(slow))


def test_parallel_libsvm_parse_matches_serial():
    """Chunked multi-core parse must be byte-identical to the single-call
    parse, for chunk boundaries landing anywhere in a line."""
    from alink_tpu.native import (get_lib, parse_libsvm_bytes,
                                  parse_libsvm_bytes_parallel,
                                  split_newline_chunks)
    if get_lib() is None:
        import pytest
        pytest.skip("native library unavailable")
    rng = np.random.RandomState(0)
    lines = []
    for i in range(5000):
        nnz = rng.randint(1, 8)
        idx = np.sort(rng.choice(200, nnz, replace=False)) + 1
        toks = " ".join(f"{j}:{rng.randn():.4f}" for j in idx)
        lines.append(f"{rng.choice([-1.0, 1.0])} {toks}")
    data = ("\n".join(lines) + "\n").encode()

    ser = parse_libsvm_bytes(data, 1)
    par = parse_libsvm_bytes_parallel(data, 1, max_workers=7)
    # force chunking even though the fixture is <4MB
    chunks = split_newline_chunks(data, 7)
    assert b"".join(chunks) == data
    assert len(chunks) > 1
    from concurrent.futures import ThreadPoolExecutor
    with ThreadPoolExecutor(len(chunks)) as ex:
        parts = list(ex.map(lambda c: parse_libsvm_bytes(c, 1), chunks))
    labels = np.concatenate([p[0] for p in parts])
    indices = np.concatenate([p[2] for p in parts])
    values = np.concatenate([p[3] for p in parts])
    nnz_offs = np.cumsum([0] + [len(p[2]) for p in parts[:-1]])
    indptr = np.concatenate(
        [parts[0][1][:1]] + [p[1][1:] + off for p, off in zip(parts, nnz_offs)])
    for got in (par, (labels, indptr, indices, values)):
        assert np.array_equal(ser[0], got[0])
        assert np.array_equal(ser[1], got[1])
        assert np.array_equal(ser[2], got[2])
        assert np.array_equal(ser[3], got[3])


def test_split_newline_chunks_edges():
    from alink_tpu.native import split_newline_chunks
    assert split_newline_chunks(b"", 4) == []
    assert split_newline_chunks(b"abc\n", 1) == [b"abc\n"]
    # no trailing newline: last partial line stays in one chunk
    data = b"a\nbb\nccc\ndddd"
    for k in range(1, 8):
        chunks = split_newline_chunks(data, k)
        assert b"".join(chunks) == data
        for c in chunks[:-1]:
            assert c.endswith(b"\n")
    # single long line, many chunks
    one = b"x" * 1000
    assert split_newline_chunks(one, 8) == [one]


def test_fast_float_path_exactness():
    """The one-pass parser's fast float path must be bit-identical to
    strtod/Python float across exponents, long mantissas, and boundary
    spellings (it falls back to strtod for anything not exactly
    representable via one division)."""
    from alink_tpu.native import get_lib, parse_libsvm_bytes
    if get_lib() is None:
        import pytest
        pytest.skip("native library unavailable")
    vals = ["1", "-1", "0", "0.5", "-0.5", "3.", ".5", "-.25",
            "1e-4", "2.5E3", "-1e10", "123456789012345678901234567890",
            "0.1234567890123456789", "9007199254740993",  # > 2^53
            "1.7976931348623157e308", "5e-324", "+2.5",
            "0.30000000000000004", "1.0000000000000002"]
    lines = []
    for i, v in enumerate(vals):
        lines.append(f"{v} {i + 1}:{v}")
    data = ("\n".join(lines) + "\n").encode()
    labels, indptr, indices, values = parse_libsvm_bytes(data, 1)
    expect = np.array([float(v) for v in vals])
    assert labels.shape == (len(vals),)
    np.testing.assert_array_equal(labels, expect)
    np.testing.assert_array_equal(values, expect)
    assert np.array_equal(indices, np.arange(len(vals), dtype=np.int32))


def test_fb16_fused_parse_matches_generic():
    """svm_fill_fb16 (one-pass field-blocked int16 parse) must agree with
    the generic CSR parse + host encode on conforming data, and return
    None (fall back) on every shape violation."""
    from alink_tpu.native import (get_lib, parse_libsvm_bytes,
                                  parse_libsvm_fb16)
    if get_lib() is None:
        import pytest
        pytest.skip("native library unavailable")
    F, S, n = 5, 32, 200
    rng = np.random.RandomState(0)
    fb = rng.randint(0, S, size=(n, F))
    y = rng.choice([-1, 1], n)
    offs = np.arange(F) * S
    lines = []
    for r in range(n):
        toks = " ".join(f"{fb[r, k] + offs[k] + 1}:1" for k in range(F))
        lines.append(f"{y[r]} {toks}")
    data = ("\n".join(lines) + "\n").encode()

    got = parse_libsvm_fb16(data, F, S, 1)
    assert got is not None
    lab, fb16 = got
    assert lab.dtype == np.float32 and fb16.dtype == np.int16
    np.testing.assert_array_equal(lab, y.astype(np.float32))
    np.testing.assert_array_equal(fb16, fb.astype(np.int16))
    # agreement with the generic path + encode
    labels, indptr, indices, values = parse_libsvm_bytes(data, 1)
    fb_generic = (indices.reshape(-1, F) - offs[None, :]).astype(np.int16)
    np.testing.assert_array_equal(fb16, fb_generic)
    np.testing.assert_array_equal(lab, labels.astype(np.float32))

    # violations -> None (fall back to the generic path)
    bad_value = data.replace(b":1 ", b":2 ", 1)
    assert parse_libsvm_fb16(bad_value, F, S, 1) is None
    assert parse_libsvm_fb16(data, F + 1, S, 1) is None        # wrong F
    missing = ("\n".join(lines[:1])
               .rsplit(" ", 1)[0] + "\n").encode()              # 4 pairs
    assert parse_libsvm_fb16(missing, F, S, 1) is None
    out_of_field = f"1 {S * F + 7}:1\n".encode()                # idx too big
    assert parse_libsvm_fb16(out_of_field, 1, S, 1) is None
