"""HOST-CALLBACK-FREE positive: host callbacks inside a compiled-path
module serialize the device on a host round trip — plain or aliased."""
import jax
from jax import debug as dbg
from jax.experimental import io_callback


def stage(ctx):
    jax.debug.print("step {s}", s=ctx)
    io_callback(print, None, ctx)
    return ctx


def stage_aliased(ctx):
    dbg.print("aliased {s}", s=ctx)    # import alias, same callback
    return ctx
