"""IO layer tests: DB source/sink over sqlite, retract sink, DirectReader
bridges, Kafka connector against the in-memory fake (reference connector
tests run builder-config without a live broker, SURVEY §4)."""

import numpy as np
import pytest

from alink_tpu.io.db import BaseDB, SqliteDB
from alink_tpu.io.directreader import (DbDataBridge, DirectReader,
                                       DirectReaderPropertiesStore,
                                       MemoryDataBridge)
from alink_tpu.io.kafka import FakeKafka, KafkaSinkStreamOp, KafkaSourceStreamOp
from alink_tpu.operator.base import StreamOperator
from alink_tpu.operator.batch.source import MemSourceBatchOp
from alink_tpu.operator.batch.source.sources import DBSourceBatchOp
from alink_tpu.operator.batch.sink.sinks import DBSinkBatchOp
from alink_tpu.operator.stream.source.sources import MemSourceStreamOp
from alink_tpu.operator.stream.sink.sinks import (CollectSinkStreamOp,
                                                  DBSinkStreamOp,
                                                  JdbcRetractSinkStreamOp)


def _rows():
    return MemSourceBatchOp([(1, "a", 0.5), (2, "b", 1.5), (3, "c", 2.5)],
                            "id LONG, name STRING, score DOUBLE")


def test_db_sink_source_roundtrip():
    db = SqliteDB("t1")
    DBSinkBatchOp(db=db, output_table_name="people").link_from(_rows())
    out = DBSourceBatchOp(db=db, input_table_name="people").collect_mtable()
    assert out.num_rows == 3 and list(out.col("name")) == ["a", "b", "c"]
    q = DBSourceBatchOp(db=db, query="SELECT id, score FROM people WHERE score > 1"
                        ).collect_mtable()
    assert q.num_rows == 2 and q.col_names == ["id", "score"]
    # overwrite vs append
    DBSinkBatchOp(db=db, output_table_name="people").link_from(_rows())
    assert db.read_table("people").num_rows == 6
    DBSinkBatchOp(db=db, output_table_name="people",
                  overwrite_sink=True).link_from(_rows())
    assert db.read_table("people").num_rows == 3
    # registry lookup by name
    assert BaseDB.of("t1") is db


def test_stream_db_and_retract_sinks():
    db = SqliteDB("t2")
    s = MemSourceStreamOp([(1, 0.1), (2, 0.2), (1, 0.9), (2, 0.8)],
                          "k LONG, v DOUBLE", batch_size=2)
    DBSinkStreamOp(db=db, output_table_name="raw").link_from(s)
    StreamOperator.execute()
    assert db.read_table("raw").num_rows == 4

    s2 = MemSourceStreamOp([(1, 0.1), (2, 0.2), (1, 0.9), (2, 0.8)],
                           "k LONG, v DOUBLE", batch_size=2)
    JdbcRetractSinkStreamOp(db=db, output_table_name="latest",
                            key_cols=["k"]).link_from(s2)
    StreamOperator.execute()
    out = db.read_table("latest")
    assert out.num_rows == 2
    got = dict(zip([int(k) for k in out.col("k")],
                   [float(v) for v in out.col("v")]))
    assert got == {1: 0.9, 2: 0.8}

    # same key twice within ONE micro-batch: last write wins
    s3 = MemSourceStreamOp([(7, 0.1), (7, 0.7)], "k LONG, v DOUBLE",
                           batch_size=2)
    JdbcRetractSinkStreamOp(db=db, output_table_name="latest",
                            key_cols=["k"]).link_from(s3)
    StreamOperator.execute()
    out2 = db.query("SELECT v FROM latest WHERE k = 7")
    assert out2.num_rows == 1 and abs(float(out2.col("v")[0]) - 0.7) < 1e-12


def test_direct_reader_policies():
    src = _rows()
    bridge = DirectReader.collect(src)
    assert isinstance(bridge, MemoryDataBridge)
    assert len(bridge.read()) == 3
    assert len(bridge.read(lambda r: r[0] > 1)) == 2

    db = SqliteDB("t3")
    DirectReaderPropertiesStore.set_properties({
        "direct.reader.policy": "db", "direct.reader.db.name": "t3"})
    try:
        bridge2 = DirectReader.collect(src)
        assert isinstance(bridge2, DbDataBridge)
        assert bridge2.read_mtable().num_rows == 3
    finally:
        DirectReaderPropertiesStore.set_properties({})


def test_kafka_fake_roundtrip():
    broker = FakeKafka()
    s = MemSourceStreamOp([(1, "x"), (2, "y")], "id LONG, tag STRING",
                          batch_size=1)
    KafkaSinkStreamOp(producer=broker, topic="t",
                      format="json").link_from(s)
    StreamOperator.execute()
    assert len(broker.topics["t"]) == 2

    src = KafkaSourceStreamOp(consumer=broker, topic="t", format="json",
                              schema_str="id LONG, tag STRING")
    sink = CollectSinkStreamOp().link_from(src)
    StreamOperator.execute()
    out = sink.get_and_remove_values()
    assert out.num_rows == 2 and list(out.col("tag")) == ["x", "y"]


def test_kafka_gated_without_client():
    # no client in this image -> ImportError; with kafka-python installed
    # the gate instead demands bootstrap_servers (ValueError)
    with pytest.raises((ImportError, ValueError)):
        KafkaSourceStreamOp(topic="t", schema_str="a LONG")
