"""Data-proc batch operators (sampling/split/id/cast family).

Re-design of operator/batch/dataproc/ (SampleBatchOp, SampleWithSizeBatchOp,
WeightSampleBatchOp, SplitBatchOp, FirstNBatchOp, AppendIdBatchOp,
NumericalTypeCastBatchOp, ShuffleBatchOp). Scaler/imputer/indexer live in
sibling modules.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ....common.mtable import MTable
from ....common.params import ParamInfo, Params, RangeValidator
from ....common.types import AlinkTypes, TableSchema
from ....params.shared import HasSeed, HasSelectedCols
from ...base import BatchOperator, TableSourceBatchOp


class SampleBatchOp(BatchOperator, HasSeed):
    """Bernoulli / with-replacement sampling (reference SampleBatchOp)."""
    RATIO = ParamInfo("ratio", float, optional=False,
                      validator=RangeValidator(0.0, 1.0))
    WITH_REPLACEMENT = ParamInfo("with_replacement", bool, default=False)

    def link_from(self, in_op: BatchOperator) -> "SampleBatchOp":
        t = in_op.get_output_table()
        rng = np.random.RandomState(self.get_seed())
        n = t.num_rows
        if self.get_with_replacement():
            m = int(round(self.get_ratio() * n))
            idx = rng.randint(0, n, size=m)
            self._output = t.take_rows(idx)
        else:
            mask = rng.rand(n) < self.get_ratio()
            self._output = t.filter_mask(mask)
        return self


class SampleWithSizeBatchOp(BatchOperator, HasSeed):
    """Exact-size sample (reference SampleWithSizeBatchOp)."""
    SIZE = ParamInfo("size", int, optional=False, validator=RangeValidator(0, None))
    WITH_REPLACEMENT = ParamInfo("with_replacement", bool, default=False)

    def link_from(self, in_op: BatchOperator) -> "SampleWithSizeBatchOp":
        t = in_op.get_output_table()
        rng = np.random.RandomState(self.get_seed())
        n = t.num_rows
        size = self.get_size()
        if self.get_with_replacement():
            idx = rng.randint(0, n, size=size)
        else:
            idx = rng.permutation(n)[:size]
        self._output = t.take_rows(np.sort(idx))
        return self


class WeightSampleBatchOp(BatchOperator, HasSeed):
    """Weighted sampling without replacement (reference WeightSampleBatchOp)."""
    WEIGHT_COL = ParamInfo("weight_col", str, optional=False)
    RATIO = ParamInfo("ratio", float, optional=False,
                      validator=RangeValidator(0.0, 1.0))

    def link_from(self, in_op: BatchOperator) -> "WeightSampleBatchOp":
        t = in_op.get_output_table()
        rng = np.random.RandomState(self.get_seed())
        w = np.asarray(t.col(self.get_weight_col()), np.float64)
        n = t.num_rows
        m = int(round(self.get_ratio() * n))
        # Efraimidis-Spirakis keys: u^(1/w) — top-m keeps weighted sample
        keys = rng.rand(n) ** (1.0 / np.maximum(w, 1e-300))
        idx = np.argsort(-keys)[:m]
        self._output = t.take_rows(np.sort(idx))
        return self


class SplitBatchOp(BatchOperator, HasSeed):
    """Random split; remainder on side output 0 (reference SplitBatchOp)."""
    FRACTION = ParamInfo("fraction", float, optional=False,
                         validator=RangeValidator(0.0, 1.0))

    def link_from(self, in_op: BatchOperator) -> "SplitBatchOp":
        t = in_op.get_output_table()
        rng = np.random.RandomState(self.get_seed())
        n = t.num_rows
        m = int(round(self.get_fraction() * n))
        perm = rng.permutation(n)
        self._output = t.take_rows(np.sort(perm[:m]))
        self._side_outputs = [t.take_rows(np.sort(perm[m:]))]
        return self


class FirstNBatchOp(BatchOperator):
    SIZE = ParamInfo("size", int, optional=False)

    def link_from(self, in_op: BatchOperator) -> "FirstNBatchOp":
        self._output = in_op.get_output_table().first_n(self.get_size())
        return self


class AppendIdBatchOp(BatchOperator):
    """Append a LONG id column (reference AppendIdBatchOp)."""
    ID_COL = ParamInfo("id_col", str, default="append_id")

    def link_from(self, in_op: BatchOperator) -> "AppendIdBatchOp":
        t = in_op.get_output_table()
        self._output = t.add_column(self.get_id_col(),
                                    np.arange(t.num_rows, dtype=np.int64),
                                    AlinkTypes.LONG)
        return self


class ShuffleBatchOp(BatchOperator, HasSeed):
    def link_from(self, in_op: BatchOperator) -> "ShuffleBatchOp":
        t = in_op.get_output_table()
        rng = np.random.RandomState(self.get_seed())
        self._output = t.take_rows(rng.permutation(t.num_rows))
        return self


class NumericalTypeCastBatchOp(BatchOperator, HasSelectedCols):
    """Cast numeric columns (reference NumericalTypeCastBatchOp)."""
    TARGET_TYPE = ParamInfo("target_type", str, default="DOUBLE")

    def link_from(self, in_op: BatchOperator) -> "NumericalTypeCastBatchOp":
        t = in_op.get_output_table()
        target = self.get_target_type().upper()
        dt = AlinkTypes.to_numpy_dtype(target)
        default = [n for n, tp in zip(t.schema.names, t.schema.types)
                   if AlinkTypes.is_numeric(tp)]
        for c in (self.get_selected_cols() or default):
            t = t.add_column(c, np.asarray(t.col(c), dtype=dt), target)
        self._output = t
        return self
