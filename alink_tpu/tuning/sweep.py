"""The sweep executor — N hyperparameter points as one BSP program.

Design contract (ISSUE 12): per-point sweep results must be **bitwise
identical to the serial fit of that point**. The PR 10/11 war story
applies — XLA's shape-dependent tiling (and FMA contraction) rounds the
same reduction differently at different shapes — so the points lane is
NOT a vmap (which would batch the data matvec into a differently-tiled
matmul). Instead the per-point kernel mirrors the serial superstep
op-for-op and the population runs under ``jax.lax.map``: a fixed-order
scan whose body executes at exactly the serial program's shapes. Same
ops, same shapes, same order → same rounding, proven bitwise by
tests/test_sweep.py on the f64 test mesh.

Execution shape:

* carry-resident hyperparameters ride as ``(points,)`` broadcast lanes
  (``swh_*``); per-point model state rides the while-loop carry with a
  ``(points,)`` leading axis (``pt_*``);
* collectives run inside the mapped body through the PR-7 manifest
  wrappers — per superstep the compiled program executes exactly
  ``points ×`` the serial program's collective set (set-identical HLO;
  pruning masks updates and therefore adds NO collectives);
* converged and pruned points FREEZE: their step output is discarded by
  a per-point ``where`` mask, so a survivor's trajectory is untouched
  by its neighbors and a frozen point's final state is its serial
  fixed point;
* ASHA successive halving runs at the engine's chunk boundaries
  (``IterativeComQueue.set_boundary`` → ``recovery.drive``): the rung
  hook fetches the per-point loss lane (the PR-4 probe discipline —
  device scalars read only at boundaries, zero host callbacks inside
  the program), keeps the top ``1/eta`` deterministically (rank by
  ``(loss, point index)``, NaN last — seed-free and reproducible), and
  flips the carry-resident alive mask. Geometry is constant, so the
  compiled program count equals the number of trace-shaping compile
  groups no matter the population size or rung schedule.

Checkpoint/resume and async snapshots (PR 2/5) work unchanged for the
whole population: the sweep carry is an ordinary engine carry, and the
rung hook re-derives its (deterministic) decisions after a resume.
"""

from __future__ import annotations

import functools as _functools
import threading
import warnings
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .plan import AshaConfig, SweepPlan

__all__ = ["SweepResult", "FtrlSweepResult", "record_sweep_fallback",
           "sweep_enabled", "sweep_eta", "sweep_rung", "sweep_optimize",
           "sweep_kmeans", "sweep_ftrl"]


# -- flags ------------------------------------------------------------------

def sweep_enabled() -> bool:
    """``ALINK_TPU_SWEEP`` (default off): route GridSearchCV /
    GridSearchTVSplit candidate loops through the sweep engine when
    every grid axis is carry-resident for a supported estimator. Folded
    into the sweep program-cache key (registry-declared), so a toggle
    can never reuse a stale compiled sweep program."""
    from ..common.flags import flag_value
    return bool(flag_value("ALINK_TPU_SWEEP", False))


def sweep_eta() -> int:
    """``ALINK_TPU_SWEEP_ETA``: the default ASHA reduction factor."""
    from ..common.flags import flag_value
    return int(flag_value("ALINK_TPU_SWEEP_ETA", 3))


def sweep_rung() -> int:
    """``ALINK_TPU_SWEEP_RUNG``: default rung period in supersteps for
    sweeps that enable pruning without an explicit AshaConfig
    (0 = ``max(1, max_iter // 4)``)."""
    from ..common.flags import flag_value
    return int(flag_value("ALINK_TPU_SWEEP_RUNG", 0))


# -- fallback observability (the serving tier's contract, shared via
# common.metrics.record_fallback_once) --------------------------------------
# A silently-serial sweep is the failure mode this exists to kill: every
# time the tuning layer declines the sweep engine it records a labelled
# counter plus ONE RuntimeWarning per (estimator, reason).

# ``reason`` must stay a SMALL ENUM (metric label): request-specific
# text goes in ``detail`` (warning only).
FALLBACK_REASONS = ("unsupported-estimator", "trace-shaping-axis",
                    "unsupported-evaluator", "sweep-error")


def record_sweep_fallback(estimator: str, reason: str,
                          detail: str = "") -> None:
    """``alink_sweep_fallback_total{estimator=, reason=}`` + one
    RuntimeWarning per (estimator, reason) pair per process."""
    from ..common.metrics import record_fallback_once
    record_fallback_once(
        "sweep", "alink_sweep_fallback_total",
        {"estimator": estimator, "reason": reason},
        f"tuning sweep falls back to the serial candidate loop for "
        f"{estimator}: {reason}{' (' + detail + ')' if detail else ''} "
        f"(recorded as alink_sweep_fallback_total{{estimator="
        f"{estimator!r},reason={reason!r}}}; this warning fires once "
        f"per estimator+reason)")


def _reset_fallback_warnings() -> None:
    """Test hook: re-arm the once-per-(estimator, reason) warnings."""
    from ..common.metrics import reset_fallback_warnings
    reset_fallback_warnings("sweep")


# -- result -----------------------------------------------------------------

@dataclass
class SweepResult:
    """Per-point outcomes of one sweep (all groups merged).

    ``values`` holds the trainer's model state per point — ``coef``
    ``(P, dim)`` for the optimizers; ``centroids`` ``(P, k, d)`` +
    ``cluster_weights`` ``(P, k)`` for k-means (lists of per-point
    arrays instead when a trace-shaping ``k`` axis makes the geometry
    ragged across compile groups). ``steps[p]`` is the
    executed superstep count of point ``p`` (== the serial fit's
    ``step_count``); ``final_loss[p]`` its last computed training loss
    (weighted inertia for k-means — computed regardless of
    ALINK_TPU_HEALTH, so rung decisions never flip with telemetry); ``alive[p]`` whether ASHA kept it; ``rungs`` the
    boundary decisions in order. ``programs`` counts compiled sweep
    programs (== trace-shaping groups)."""
    trainer: str
    points: List[Dict[str, Any]]
    values: Dict[str, np.ndarray]
    steps: np.ndarray
    final_loss: np.ndarray
    alive: np.ndarray
    converged: np.ndarray
    loss_curves: List[np.ndarray]
    rungs: List[Dict[str, Any]] = field(default_factory=list)
    programs: int = 1

    @property
    def num_points(self) -> int:
        return len(self.points)

    @property
    def pruned_at(self) -> Dict[int, int]:
        out: Dict[int, int] = {}
        for r in self.rungs:
            for i in r["pruned"]:
                out.setdefault(int(i), int(r["step"]))
        return out

    def survivors(self) -> List[int]:
        return [int(i) for i in np.flatnonzero(self.alive)]

    @property
    def best(self) -> int:
        """The winning point: lowest final loss among survivors, ties
        broken by lowest point index — deterministic and seed-free."""
        live = np.flatnonzero(self.alive)
        if live.size == 0:          # defensive: never prunes to zero
            live = np.arange(len(self.points))
        key = np.where(np.isfinite(self.final_loss[live]),
                       self.final_loss[live], np.inf)
        order = np.lexsort((live, key))
        return int(live[order[0]])


# -- shared kernel plumbing -------------------------------------------------

def _sweep_criterion(ctx):
    """Stop when every still-alive point has converged (replicated)."""
    import jax.numpy as jnp
    return jnp.all(ctx.get_obj("pt_conv") | ~ctx.get_obj("sw_alive"))


def _reg_loss(obj, coef, l1, l2):
    """``OptimObjFunc.regular_loss`` with (l1, l2) as traced per-point
    lanes — same association order as the serial python-float path, so
    the rounding is bitwise identical (0.5·l2 is an exact halving in
    both)."""
    import jax.numpy as jnp
    m = obj._reg_mask(coef)
    return (0.5 * l2 * ((coef * m) ** 2).sum()
            + l1 * jnp.abs(coef * m).sum())


def _l2_grad(obj, coef, l2):
    """``OptimObjFunc.l2_grad`` with a traced l2 lane (same op order)."""
    return l2 * coef * obj._reg_mask(coef)


def _freeze_cond(active, step_fn, pc_p):
    """Per-point freeze: a pruned or converged point SKIPS its step
    (``lax.cond`` — the frozen branch returns the carry untouched, so
    pruning buys real wall clock, not just masked writes). The
    predicate is replicated (computed from the replicated alive/conv
    lanes), so every worker takes the same branch and the live branch's
    collectives never deadlock; the compiled program's collective SET is
    the live branch's — identical to the unswept program's — no matter
    how many points are frozen."""
    import jax
    return jax.lax.cond(active, step_fn, lambda pc: dict(pc), pc_p)


def _make_asha_hook(asha: AshaConfig, num_points: int,
                    rung_log: List[Dict[str, Any]]) -> Callable:
    """The chunk-boundary rung: fetch the per-point loss lane (ONE
    batched device_get of three small arrays), keep the deterministic
    top ``ceil(alive/eta)``, flip the carry-resident alive mask. Runs
    AFTER the boundary snapshot published and re-runs after a resume —
    the decision is a pure function of the carry, so kill-and-resume
    reproduces it bitwise.

    Once the population is down to ``min_points`` there are no more
    decisions to make: the hook marks itself ``exhausted`` and the
    driver (persistence off) runs the remaining supersteps as ONE chunk
    — rung boundaries are host syncs, and paying them for a settled
    population is pure overhead."""

    def hook(stacked, step):
        import jax
        alive_s, conv_s, loss_s = jax.device_get(
            [stacked["sw_alive"], stacked["pt_conv"],
             stacked["pt_cur_loss"]])
        alive = np.asarray(alive_s)[0]
        conv = np.asarray(conv_s)[0]
        loss = np.asarray(loss_s)[0].astype(np.float64)
        live = np.flatnonzero(alive)
        keep_n = max(int(asha.min_points),
                     int(np.ceil(live.size / float(asha.eta))))
        pruned: List[int] = []
        new_alive = alive
        if keep_n < live.size:
            # deterministic, seed-free: rank by (loss, point index),
            # non-finite losses last — the reproducibility contract
            key = np.where(np.isfinite(loss[live]), loss[live], np.inf)
            order = np.lexsort((live, key))
            keep = live[order[:keep_n]]
            new_alive = np.zeros(num_points, bool)
            new_alive[keep] = True
            pruned = sorted(int(i) for i in set(live) - set(keep))
        rung_log.append({"step": int(step),
                         "alive_before": int(live.size),
                         "alive_after": int(np.count_nonzero(new_alive)),
                         "pruned": pruned})
        if np.count_nonzero(new_alive) <= int(asha.min_points):
            hook.exhausted = True
        if not pruned:
            return None
        from ..common.metrics import get_registry, metrics_enabled
        if metrics_enabled():
            get_registry().inc("alink_sweep_pruned_points_total",
                               len(pruned))
        nw = np.asarray(alive_s).shape[0]
        out = dict(stacked)
        out["sw_alive"] = np.broadcast_to(new_alive,
                                          (nw, num_points)).copy()
        if np.all(conv | ~new_alive):
            # the surviving population is fully converged: stop now
            # instead of burning one more (frozen) chunk
            out["__stop"] = np.ones(nw, bool)
        return out

    hook.exhausted = False
    return hook


def _run_sweep_queue(*, kind: str, stage, parts: Dict[str, Any],
                     bcast: Dict[str, Any], env, max_iter: int, seed: int,
                     key_tail: Tuple, num_points: int,
                     asha: Optional[AshaConfig],
                     checkpoint_dir: Optional[str],
                     checkpoint_keep: int, resume_from: Optional[str],
                     rung_log: List[Dict[str, Any]]):
    """Build and exec the ONE swept BSP program of a compile group.

    This is the sweep's program factory (an alink-lint factory root):
    every flag read reachable from here must fold into the program key
    or be registry-declared key-neutral. ``ALINK_TPU_SWEEP`` folds —
    resolved at the plan derivation site (``common/plan.sweep_plan``,
    the ENV-KEY-FOLD checked site; the legacy program-key tuple is
    byte-identical) — and the ASHA knobs are key-neutral (host
    boundary pruning of a carry lane; chunk limits are traced
    scalars)."""
    from ..common import compileledger
    from ..common.plan import legacy_sweep_program_key, sweep_plan
    from ..engine import IterativeComQueue

    compileledger.subsystem_start("sweep")
    queue = IterativeComQueue(env=env, max_iter=int(max_iter),
                              seed=int(seed))
    for k, v in parts.items():
        queue.init_with_partitioned_data(k, v)
    for k, v in bcast.items():
        queue.init_with_broadcast_data(k, v)
    queue.add(stage)
    queue.set_compare_criterion(_sweep_criterion)
    queue.set_program_key(
        legacy_sweep_program_key(sweep_plan(kind, tuple(key_tail))))
    if checkpoint_dir:
        queue.set_checkpoint(checkpoint_dir,
                             every=(asha.rung if asha is not None else 1),
                             keep_last=int(checkpoint_keep),
                             resume_from=resume_from)
    if asha is not None:
        queue.set_boundary(asha.rung,
                           _make_asha_hook(asha, num_points, rung_log))
    return queue.exec()


def _group_paths(checkpoint_dir: Optional[str],
                 resume_from: Optional[str], gi: int,
                 n_groups: int) -> Tuple[Optional[str], Optional[str]]:
    """Per-compile-group checkpoint/resume directories: multi-group
    sweeps snapshot each group under its own subdirectory so the
    signatures can never collide."""
    if not checkpoint_dir or n_groups <= 1:
        return checkpoint_dir, resume_from
    import os
    return (os.path.join(checkpoint_dir, f"group{gi}"),
            os.path.join(resume_from, f"group{gi}") if resume_from
            else None)


def _resolve_asha(asha, max_iter: int) -> Optional[AshaConfig]:
    """``None``/``False`` = no pruning; ``True`` = flag-driven defaults
    (``ALINK_TPU_SWEEP_ETA`` / ``ALINK_TPU_SWEEP_RUNG``); an
    ``AshaConfig`` passes through."""
    if not asha:
        return None
    if isinstance(asha, AshaConfig):
        return asha
    rung = sweep_rung() or max(1, int(max_iter) // 4)
    return AshaConfig(rung=rung, eta=sweep_eta())


# -- optimizer sweep kernels ------------------------------------------------
# Each point step mirrors the serial stage code in
# operator/common/optim/optimizers.py OP-FOR-OP (same helper calls, same
# association order); the only differences are (a) the carry-resident
# hypers arrive as traced per-point scalars and (b) the two AllReduce
# stages become manifest_psum calls at the same positions. The bitwise
# parity test (tests/test_sweep.py) is the load-bearing check that this
# mirror never drifts.

_QN_KEYS = ("coef", "coef_prev", "grad_prev", "step_scale", "loss_curve",
            "conv", "cur_loss")
_QN_MEM_KEYS = ("sk", "yk", "pos", "nvalid")


def _qn_point_step(obj, shard, pc, hyp, step, nw, axis, m, owlqn, dtype,
                   dim, steps_base, max_iter):
    import jax
    import jax.numpy as jnp

    from ..engine.communication import manifest_psum
    from ..operator.common.optim.optimizers import (_NUM_SEARCH_STEP,
                                                    _TINY, _pseudo_grad,
                                                    _two_loop)
    coef = pc["coef"]
    g, loss, wsum, eta = obj.calc_grad_eta_shard(shard, coef)
    glw = jnp.concatenate([g, jnp.stack([loss, wsum])])
    glw = jnp.asarray(manifest_psum(glw, axis, name="sweep_glw",
                                    num_workers=nw))
    l1, l2 = hyp["l1"], hyp["l2"]
    W = jnp.maximum(glw[dim + 1], _TINY)
    g_plain = glw[:dim] / W + _l2_grad(obj, coef, l2)
    loss_total = glw[dim] / W + _reg_loss(obj, coef, l1, l2)
    loss_curve = jax.lax.dynamic_update_index_in_dim(
        pc["loss_curve"], loss_total.astype(dtype), step - 1, 0)
    if owlqn:
        g_dir = _pseudo_grad(g_plain, coef, l1, obj._reg_mask(coef))
    else:
        g_dir = g_plain
    gnorm = jnp.linalg.norm(g_dir) / jnp.maximum(1.0, jnp.linalg.norm(coef))
    conv = gnorm < hyp["eps"]
    out = {"coef_prev": coef, "grad_prev": g_plain,
           "loss_curve": loss_curve, "conv": conv,
           "cur_loss": loss_total.astype(dtype)}
    if m > 0:
        push = step > 1
        snew = coef - pc["coef_prev"]
        ynew = g_plain - pc["grad_prev"]
        pos = pc["pos"]
        sk = jnp.where(push, pc["sk"].at[pos].set(snew), pc["sk"])
        yk = jnp.where(push, pc["yk"].at[pos].set(ynew), pc["yk"])
        pos = jnp.where(push, (pos + 1) % m, pos)
        nvalid = jnp.where(push, jnp.minimum(pc["nvalid"] + 1, m),
                           pc["nvalid"])
        out.update(sk=sk, yk=yk, pos=pos, nvalid=nvalid)
        d = _two_loop(g_dir, sk, yk, pos, nvalid, m)
    else:
        d = g_dir
    if owlqn:
        d = jnp.where(d * g_dir > 0, d, 0.0)
    steps = (hyp["lr"] * jnp.asarray(steps_base)) * pc["step_scale"]
    line = obj.line_losses_shard(shard, coef, d, steps, eta0=eta)
    line = jnp.asarray(manifest_psum(line, axis, name="sweep_line",
                                     num_workers=nw))
    reg = jax.vmap(lambda s: _reg_loss(obj, coef - s * d, l1, l2))(steps)
    total = line / W + reg
    best = jnp.argmin(total)
    s_best = steps[best]
    new_coef = coef - s_best * d
    if owlqn:
        orthant = jnp.where(coef != 0, jnp.sign(coef), -jnp.sign(g_dir))
        new_coef = jnp.where(new_coef * orthant < 0, 0.0, new_coef)
    scale = pc["step_scale"]
    scale = jnp.where(best == 0, scale * 0.25,
                      jnp.where(best == 1, scale * 2.0,
                                jnp.where(best == _NUM_SEARCH_STEP,
                                          scale * 0.5, scale)))
    out["coef"] = new_coef
    out["step_scale"] = jnp.clip(scale, 1e-10, 1e6)
    return out


def _sgd_point_step(obj, shard, pc, hyp, step, key, nw, axis, dtype, dim):
    import jax
    import jax.numpy as jnp

    from ..engine.communication import manifest_psum
    from ..operator.common.optim.optimizers import _TINY
    coef = pc["coef"]
    mask = jax.random.bernoulli(key, hyp["frac"], shard["y"].shape)
    sub = dict(shard)
    sub["w"] = shard["w"] * mask.astype(shard["w"].dtype)
    g, loss, wsum = obj.calc_grad_shard(sub, coef)
    glw = jnp.concatenate([g, jnp.stack([loss, wsum])])
    glw = jnp.asarray(manifest_psum(glw, axis, name="sweep_glw",
                                    num_workers=nw))
    l1, l2 = hyp["l1"], hyp["l2"]
    wsum = glw[dim + 1]
    nonempty = wsum > 0
    W = jnp.maximum(wsum, _TINY)
    gg = glw[:dim] / W + _l2_grad(obj, coef, l2)
    lr = hyp["lr"] / jnp.sqrt(step.astype(dtype))
    new_coef = coef - lr * gg
    # the serial path applies the L1 prox only when obj.l1 > 0 (a
    # trace-time branch); the lane twin selects on the traced l1 — the
    # branches agree bitwise at l1 == 0 (soft-threshold with thr 0 is
    # the identity up to signed zeros)
    thr = l1 * lr * obj._reg_mask(coef)
    soft = jnp.sign(new_coef) * jnp.maximum(jnp.abs(new_coef) - thr, 0.0)
    new_coef = jnp.where(l1 > 0, soft, new_coef)
    new_coef = jnp.where(nonempty, new_coef, coef)
    loss_total = glw[dim] / W + _reg_loss(obj, coef, l1, l2)
    conv = nonempty & (jnp.linalg.norm(lr * gg) <
                       hyp["eps"] * jnp.maximum(1.0, jnp.linalg.norm(coef)))
    return {"coef": new_coef,
            "loss_curve": jax.lax.dynamic_update_index_in_dim(
                pc["loss_curve"], loss_total.astype(dtype), step - 1, 0),
            "conv": conv, "cur_loss": loss_total.astype(dtype)}


def _newton_point_step(obj, shard, pc, hyp, step, nw, axis, dtype, dim):
    import jax
    import jax.numpy as jnp

    from ..engine.communication import manifest_psum
    from ..operator.common.optim.optimizers import _TINY
    coef = pc["coef"]
    H, g, loss, wsum = obj.hessian_shard(shard, coef)
    # the serial program reduces H and glw through two separate
    # AllReduce stages, in this order — mirrored exactly
    H = jnp.asarray(manifest_psum(H, axis, name="sweep_H",
                                  num_workers=nw))
    glw = jnp.concatenate([g, jnp.stack([loss, wsum])])
    glw = jnp.asarray(manifest_psum(glw, axis, name="sweep_glw",
                                    num_workers=nw))
    l1, l2 = hyp["l1"], hyp["l2"]
    W = jnp.maximum(glw[dim + 1], _TINY)
    gg = glw[:dim] / W + _l2_grad(obj, coef, l2)
    Hn = H / W
    reg_diag = l2 * obj._reg_mask(coef) + 1e-8
    Hn = Hn + jnp.diag(reg_diag.astype(Hn.dtype))
    d = jnp.linalg.solve(Hn, gg)
    loss_total = glw[dim] / W + _reg_loss(obj, coef, l1, l2)
    conv = jnp.linalg.norm(d) < \
        hyp["eps"] * jnp.maximum(1.0, jnp.linalg.norm(coef))
    return {"coef": coef - d,
            "loss_curve": jax.lax.dynamic_update_index_in_dim(
                pc["loss_curve"], loss_total.astype(dtype), step - 1, 0),
            "conv": conv, "cur_loss": loss_total.astype(dtype)}


def _make_optimizer_stage(obj, data_keys: Tuple[str, ...], P: int,
                          dim: int, dtype, method: str, m: int,
                          max_iter: int, steps_base: np.ndarray):
    """One engine stage sweeping P points of one optimizer family.

    The per-point body runs under ``jax.lax.map`` — the fixed-order
    points lane. Frozen (converged/pruned) points still compute (the
    program's geometry and collective set never depend on the alive
    mask) but their output is discarded by the freeze merge."""
    import jax
    import jax.numpy as jnp

    owlqn = method == "OWLQN"
    sgd = method == "SGD"
    newton = method == "NEWTON"
    pt_keys = (("coef", "loss_curve", "conv", "cur_loss")
               if (sgd or newton) else
               _QN_KEYS + (_QN_MEM_KEYS if m > 0 else ()))
    hyp_names = ("lr", "eps", "l1", "l2") + (("frac",) if sgd else ())

    def stage(ctx):
        shard = {k: ctx.get_obj(k) for k in data_keys}
        hyp = {n: ctx.get_obj("swh_" + n) for n in hyp_names}
        step = ctx.step_no
        if ctx.is_init_step:
            c0 = ctx.get_obj("swh_coef0")
            pc = {"coef": c0,
                  "loss_curve": jnp.full((P, max_iter), jnp.nan, dtype),
                  "conv": jnp.zeros((P,), bool),
                  "cur_loss": jnp.full((P,), jnp.inf, dtype)}
            if not (sgd or newton):
                pc["coef_prev"] = c0
                pc["grad_prev"] = jnp.zeros((P, dim), dtype)
                pc["step_scale"] = jnp.ones((P,), dtype)
                if m > 0:
                    pc["sk"] = jnp.zeros((P, m, dim), dtype)
                    pc["yk"] = jnp.zeros((P, m, dim), dtype)
                    pc["pos"] = jnp.zeros((P,), jnp.int32)
                    pc["nvalid"] = jnp.zeros((P,), jnp.int32)
            alive = jnp.ones((P,), bool)
            steps_done = jnp.zeros((P,), jnp.int32)
        else:
            pc = {k: ctx.get_obj("pt_" + k) for k in pt_keys}
            alive = ctx.get_obj("sw_alive")
            steps_done = ctx.get_obj("sw_steps")
        active = alive & jnp.logical_not(pc["conv"])
        nw = ctx.num_task
        axis = ctx.AXIS
        key = ctx.rng_key() if sgd else None

        def one(args):
            pc_p, hyp_p, act = args

            def live(pc_q):
                if sgd:
                    return _sgd_point_step(obj, shard, pc_q, hyp_p, step,
                                           key, nw, axis, dtype, dim)
                if newton:
                    return _newton_point_step(obj, shard, pc_q, hyp_p,
                                              step, nw, axis, dtype, dim)
                return _qn_point_step(obj, shard, pc_q, hyp_p, step, nw,
                                      axis, m, owlqn, dtype, dim,
                                      steps_base, max_iter)

            return _freeze_cond(act, live, pc_p)

        out = jax.lax.map(one, (pc, hyp, active))
        for k in pt_keys:
            ctx.put_obj("pt_" + k, out[k])
        ctx.put_obj("sw_alive", alive)
        ctx.put_obj("sw_steps", steps_done + active.astype(jnp.int32))
        # population-health probes (PR 4 channel): replicated scalars
        # only — no collective of their own
        lane = jnp.where(alive, out["cur_loss"], jnp.inf)
        ctx.probe("sweep.best_loss", lane.min())
        ctx.probe("sweep.alive", alive.sum())

    stage.__name__ = f"sweep_{method.lower()}"
    return stage


def _optimize_dtype(data) -> np.dtype:
    """The serial optimizer's dtype rule, verbatim."""
    dtype = np.dtype(getattr(data["y"], "dtype", None)
                     or np.asarray(data["y"]).dtype)
    if dtype not in (np.float32, np.float64):
        dtype = np.float32
    return dtype


def sweep_optimize(obj, data: Dict[str, np.ndarray], params, points:
                   Sequence[Dict[str, Any]], env=None, warm_starts=None,
                   asha=None, checkpoint_dir: Optional[str] = None,
                   checkpoint_keep: int = 3,
                   resume_from: Optional[str] = None) -> SweepResult:
    """Sweep N hyperparameter points of the iterative optimizers
    (LBFGS/OWLQN/GD/SGD/Newton) as one BSP program per compile group.

    ``obj``/``data``/``params`` are exactly :func:`~alink_tpu.operator.
    common.optim.optimizers.optimize`'s inputs; ``points`` is a list of
    per-point override dicts over the carry-resident axes
    (``learning_rate``, ``epsilon``, ``l1``, ``l2``,
    ``mini_batch_fraction``) and/or trace-shaping axes (``method``,
    ``max_iter``, ``seed`` — each distinct combination compiles its own
    group program). ``warm_starts`` is an optional ``(P, dim)`` stack.
    ``asha`` is ``None`` (train every point to completion — the
    GridSearchCV mode), ``True`` (flag-driven schedule) or an
    :class:`~alink_tpu.tuning.plan.AshaConfig`.

    Per-point results are bitwise identical to ``optimize()`` with that
    point's parameters (the load-bearing tests in tests/test_sweep.py).
    """
    from ..operator.common.optim.optimizers import (_HISTORY,
                                                    _NUM_SEARCH_STEP,
                                                    _fb_precompute_ok)
    base_method = (params.method or "LBFGS").upper()
    plan = SweepPlan("optimizer", [dict(p) for p in points],
                     base={"method": base_method,
                           "max_iter": int(params.max_iter),
                           "seed": int(params.seed)})
    dim = obj.dim
    dtype = _optimize_dtype(data)
    data = dict(data)
    if _fb_precompute_ok(obj, data):
        # the serial trainers' one-hot-factor precompute, mirrored so a
        # swept fit runs the identical program family (optimizers.py)
        import jax.numpy as jnp

        from ..engine.comqueue import lazy_jit
        from ..ops.fieldblock import fb_onehot_parts
        A, B = lazy_jit(fb_onehot_parts, static_argnums=(1,))(
            jnp.asarray(data["fb_idx"]), obj.fb_meta)
        data["fb_A"], data["fb_B"] = A, B
    data_keys = tuple(data)

    P_total = plan.num_points
    coefs = np.zeros((P_total, dim), dtype)
    steps_all = np.zeros(P_total, np.int64)
    loss_all = np.full(P_total, np.nan)
    alive_all = np.ones(P_total, bool)
    conv_all = np.zeros(P_total, bool)
    curves: List[Optional[np.ndarray]] = [None] * P_total
    rung_log_all: List[Dict[str, Any]] = []

    from ..engine.comqueue import freeze_config as _freeze
    groups = plan.groups()
    for gi, (tkey, idxs) in enumerate(groups):
        gcfg = dict(tkey)
        method = str(gcfg["method"] or "LBFGS").upper()
        max_iter = int(gcfg["max_iter"])
        seed = int(gcfg["seed"])
        m = {"LBFGS": _HISTORY, "OWLQN": _HISTORY, "GD": 0}.get(method, 0)
        if method not in ("LBFGS", "OWLQN", "GD", "SGD", "NEWTON"):
            raise ValueError(f"unknown optim method {method!r}")
        P = len(idxs)
        pts = [plan.points[i] for i in idxs]

        def lane(name, default):
            return np.asarray([pt.get(name, default) for pt in pts], dtype)

        bcast = {"swh_lr": lane("learning_rate", params.learning_rate),
                 "swh_eps": lane("epsilon", params.epsilon),
                 "swh_l1": lane("l1", obj.l1),
                 "swh_l2": lane("l2", obj.l2)}
        if method == "SGD":
            # the frac lane stays CANONICAL-float (f64; the engine
            # downcasts with x64 off): jax.random.bernoulli draws its
            # uniforms in dtype(p), and the serial path passes a python
            # float — a data-dtype lane would draw f32 uniforms on an
            # x64 rig with f32 training data and break bitwise parity
            bcast["swh_frac"] = np.asarray(
                [pt.get("mini_batch_fraction",
                        params.mini_batch_fraction) for pt in pts],
                np.float64)
        if warm_starts is None:
            c0 = np.zeros((P_total, dim), dtype)
        else:
            c0 = np.asarray(warm_starts, dtype)
        bcast["swh_coef0"] = c0[np.asarray(idxs)]
        # the serial line-search ladder WITHOUT its lr factor (lr is a
        # per-point lane); [0, 2^1, 2^0, ..., 2^-8] in data dtype —
        # multiplying the lane back in is a power-of-two scaling, exact
        steps_base = np.concatenate(
            [[0.0], np.power(2.0, 1 - np.arange(_NUM_SEARCH_STEP,
                                                dtype=np.float64))]
        ).astype(dtype)
        stage = _make_optimizer_stage(obj, data_keys, P, dim, dtype,
                                      method, m, max_iter, steps_base)
        rung_log: List[Dict[str, Any]] = []
        ck_dir, rs = _group_paths(checkpoint_dir, resume_from, gi,
                                  len(groups))
        res = _run_sweep_queue(
            kind=f"opt_{method.lower()}", stage=stage, parts=data,
            bcast=bcast, env=env, max_iter=max_iter, seed=seed,
            key_tail=(m, str(dtype), data_keys, _freeze(obj)),
            num_points=P, asha=_resolve_asha(asha, max_iter),
            checkpoint_dir=ck_dir, checkpoint_keep=checkpoint_keep,
            resume_from=rs, rung_log=rung_log)
        g_coef = np.asarray(res.get("pt_coef"))
        g_steps = np.asarray(res.get("sw_steps"))
        g_loss = np.asarray(res.get("pt_cur_loss"))
        g_alive = np.asarray(res.get("sw_alive"))
        g_conv = np.asarray(res.get("pt_conv"))
        g_curves = np.asarray(res.get("pt_loss_curve"))
        for j, i in enumerate(idxs):
            coefs[i] = g_coef[j]
            steps_all[i] = g_steps[j]
            loss_all[i] = g_loss[j]
            alive_all[i] = g_alive[j]
            conv_all[i] = g_conv[j]
            curves[i] = np.array(g_curves[j][:int(g_steps[j])])
        for r in rung_log:
            rung_log_all.append(
                {**r, "group": gi,
                 "pruned": [int(idxs[p]) for p in r["pruned"]]})
        res.release()

    return SweepResult(trainer="optimizer", points=plan.points,
                       values={"coef": coefs}, steps=steps_all,
                       final_loss=loss_all, alive=alive_all,
                       converged=conv_all,
                       loss_curves=[c if c is not None
                                    else np.zeros(0, dtype)
                                    for c in curves],
                       rungs=rung_log_all, programs=len(groups))


# -- k-means sweep ----------------------------------------------------------

def _make_kmeans_stage(P: int, k: int, d: int, dtype, distance_type: str,
                       max_iter: int):
    """The Lloyd superstep of ``kmeans_train`` with a points lane: per
    point its own centroid block and tolerance; the init seed sweeps as
    DATA (the stacked host-computed init centroids), so a seed axis
    never recompiles."""
    import jax
    import jax.numpy as jnp

    from ..engine.communication import manifest_psum
    from ..operator.common.clustering.kmeans import assign_clusters

    def stage(ctx):
        block = ctx.get_obj("data")
        Xb, wb = block[:, :d], block[:, d]
        tol = ctx.get_obj("swh_tol")
        step = ctx.step_no
        if ctx.is_init_step:
            pc = {"centroids": ctx.get_obj("swh_init_centroids"),
                  "movement": jnp.full((P,), jnp.inf, dtype),
                  "cluster_weights": jnp.zeros((P, k), dtype),
                  "conv": jnp.zeros((P,), bool),
                  "cur_loss": jnp.full((P,), jnp.inf, dtype)}
            alive = jnp.ones((P,), bool)
            steps_done = jnp.zeros((P,), jnp.int32)
        else:
            pc = {n: ctx.get_obj("pt_" + n)
                  for n in ("centroids", "movement", "cluster_weights",
                            "conv", "cur_loss")}
            alive = ctx.get_obj("sw_alive")
            steps_done = ctx.get_obj("sw_steps")
        active = alive & jnp.logical_not(pc["conv"])
        nw = ctx.num_task
        axis = ctx.AXIS

        def one(args):
            pc_p, tol_p, act = args

            def live(pc_q):
                C = pc_q["centroids"]
                ids, dist = assign_clusters(Xb, C, distance_type)
                onehot = jax.nn.one_hot(ids, k, dtype=dtype) * wb[:, None]
                sums = onehot.T @ Xb
                cnts = onehot.sum(0)
                buf = jnp.concatenate([sums, cnts[:, None]], 1)
                # the inertia row (the serial trainer's ALINK_TPU_HEALTH
                # probe row) rides the buf psum UNCONDITIONALLY here: it
                # is the ASHA pruning signal, and rung decisions must
                # not flip with an observability flag. The psum reduces
                # elementwise, so the extra row cannot perturb the
                # centroid block — per-point parity with the serial
                # trainer holds under either flag setting (tested).
                inertia = jnp.concatenate(
                    [(dist * wb).sum().reshape(1, 1),
                     jnp.zeros((1, d), dtype)], 1)
                buf = jnp.concatenate([buf, inertia.astype(dtype)], 0)
                buf = jnp.asarray(manifest_psum(buf, axis,
                                                name="sweep_buf",
                                                num_workers=nw))
                cur = buf[k, 0]
                buf = buf[:k]
                sums2, cnts2 = buf[:, :d], buf[:, d]
                newC = jnp.where(cnts2[:, None] > 0,
                                 sums2 / jnp.maximum(cnts2[:, None],
                                                     1e-12), C)
                movement = jnp.sqrt(((newC - C) ** 2).sum(1)).max()
                return {"centroids": newC, "movement": movement,
                        "cluster_weights": cnts2, "conv": movement < tol_p,
                        "cur_loss": cur.astype(dtype)}

            return _freeze_cond(act, live, pc_p)

        out = jax.lax.map(one, (pc, tol, active))
        for n in ("centroids", "movement", "cluster_weights", "conv",
                  "cur_loss"):
            ctx.put_obj("pt_" + n, out[n])
        ctx.put_obj("sw_alive", alive)
        ctx.put_obj("sw_steps", steps_done + active.astype(jnp.int32))
        lane = jnp.where(alive, out["cur_loss"], jnp.inf)
        ctx.probe("sweep.best_loss", lane.min())
        ctx.probe("sweep.alive", alive.sum())

    stage.__name__ = "sweep_kmeans"
    return stage


def sweep_kmeans(X: np.ndarray, k: int, points: Sequence[Dict[str, Any]],
                 max_iter: int = 50, tol: float = 1e-4,
                 distance_type: str = "EUCLIDEAN",
                 init: str = "K_MEANS_PARALLEL", seed: int = 0, env=None,
                 sample_weight: Optional[np.ndarray] = None, asha=None,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_keep: int = 3,
                 resume_from: Optional[str] = None) -> SweepResult:
    """Sweep N ``kmeans_train`` points as one Lloyd program per compile
    group. Carry-resident axes: ``tol`` and the init ``seed`` (the
    stacked init centroids are host data, so a seed grid shares one
    program); trace-shaping axes: ``k``, ``distance_type``, ``init``,
    ``max_iter``. Per-point centroids are bitwise identical to
    ``kmeans_train`` with that point's parameters."""
    from ..operator.common.clustering.kmeans import (kmeans_parallel_init,
                                                     kmeans_plus_plus_init,
                                                     random_init)
    X = np.asarray(X)
    n, d = X.shape
    dt = X.dtype
    plan = SweepPlan("kmeans", [dict(p) for p in points],
                     base={"k": int(k), "distance_type": distance_type,
                           "init": init, "max_iter": int(max_iter)})
    w = np.ones(n, dt) if sample_weight is None \
        else np.asarray(sample_weight, dt)
    data = np.concatenate([X, w[:, None]], axis=1)

    P_total = plan.num_points
    # per-point model state collects as LISTS first: a k axis is
    # trace-shaping, so different compile groups may carry different
    # centroid geometries — stacked to (P, k, d) only when uniform
    cent_list: List[Optional[np.ndarray]] = [None] * P_total
    weight_list: List[Optional[np.ndarray]] = [None] * P_total
    steps_all = np.zeros(P_total, np.int64)
    loss_all = np.full(P_total, np.nan)
    alive_all = np.ones(P_total, bool)
    conv_all = np.zeros(P_total, bool)
    curves: List[np.ndarray] = [np.zeros(0, dt)] * P_total
    rung_log_all: List[Dict[str, Any]] = []

    groups = plan.groups()
    for gi, (tkey, idxs) in enumerate(groups):
        gcfg = dict(tkey)
        g_k = int(gcfg["k"])
        g_dist = str(gcfg["distance_type"])
        g_init = str(gcfg["init"]).upper()
        g_iter = int(gcfg["max_iter"])
        pts = [plan.points[i] for i in idxs]
        P = len(idxs)
        init_stack = np.zeros((P, g_k, d), dt)
        for j, pt in enumerate(pts):
            s = int(pt.get("seed", seed))
            if g_init == "RANDOM":
                c0 = random_init(X, g_k, s)
            elif g_init in ("K_MEANS_PARALLEL", "KMEANS_PARALLEL"):
                c0 = kmeans_parallel_init(X, g_k, seed=s, env=env)
            else:
                c0 = kmeans_plus_plus_init(X, g_k, s)
            init_stack[j] = c0.astype(dt)
        bcast = {"swh_tol": np.asarray(
                     [pt.get("tol", tol) for pt in pts], dt),
                 "swh_init_centroids": init_stack}
        stage = _make_kmeans_stage(P, g_k, d, dt, g_dist, g_iter)
        rung_log: List[Dict[str, Any]] = []
        ck_dir, rs = _group_paths(checkpoint_dir, resume_from, gi,
                                  len(groups))
        res = _run_sweep_queue(
            kind="kmeans", stage=stage, parts={"data": data},
            bcast=bcast, env=env, max_iter=g_iter, seed=int(seed),
            key_tail=(g_k, d, g_dist, str(dt)),
            num_points=P, asha=_resolve_asha(asha, g_iter),
            checkpoint_dir=ck_dir, checkpoint_keep=checkpoint_keep,
            resume_from=rs, rung_log=rung_log)
        g_c = np.asarray(res.get("pt_centroids"))
        g_w = np.asarray(res.get("pt_cluster_weights"))
        g_steps = np.asarray(res.get("sw_steps"))
        g_loss = np.asarray(res.get("pt_cur_loss"))
        g_alive = np.asarray(res.get("sw_alive"))
        g_conv = np.asarray(res.get("pt_conv"))
        for j, i in enumerate(idxs):
            cent_list[i] = np.array(g_c[j])
            weight_list[i] = np.array(g_w[j])
            steps_all[i] = g_steps[j]
            loss_all[i] = g_loss[j]
            alive_all[i] = g_alive[j]
            conv_all[i] = g_conv[j]
        for r in rung_log:
            rung_log_all.append(
                {**r, "group": gi,
                 "pruned": [int(idxs[p]) for p in r["pruned"]]})
        res.release()

    uniform = len({c.shape for c in cent_list}) == 1
    return SweepResult(trainer="kmeans", points=plan.points,
                       values={"centroids": (np.stack(cent_list)
                                             if uniform else cent_list),
                               "cluster_weights": (np.stack(weight_list)
                                                   if uniform
                                                   else weight_list)},
                       steps=steps_all, final_loss=loss_all,
                       alive=alive_all, converged=conv_all,
                       loss_curves=curves, rungs=rung_log_all,
                       programs=len(groups))


# -- FTRL hyperparameter sweeps (ISSUE 13 satellite; ROADMAP item 3
# leftover) -----------------------------------------------------------------

@dataclass
class FtrlSweepResult:
    """Per-point outcomes of one FTRL staleness-kernel sweep.

    ``z``/``n``: (P, dim_pad) final FTRL state per point — each lane
    round-equal to a serial staleness-kernel drain with that point's
    hyperparameters at the pinned 1e-12 tolerance, and BITWISE
    independent of the population (a lane's result never changes when
    other points join or leave the sweep — tests/test_sweep.py);
    ``margins``:
    (P, total_rows) pre-update margins in arrival order;
    ``pv_logloss``: per-point progressive-validation logloss over the
    whole drain (margins are computed at pre-update weights in the
    staleness kernel, so this is the honest online loss — the
    winner-selection lane); ``programs``: compiled program count (1
    for a carry-resident grid); ``fallback``: True when a
    trace-shaping axis forced the recorded serial path."""
    points: List[Dict[str, Any]]
    z: np.ndarray
    n: np.ndarray
    margins: np.ndarray
    pv_logloss: np.ndarray
    programs: int
    fallback: bool = False

    @property
    def num_points(self) -> int:
        return len(self.points)

    @property
    def best(self) -> int:
        """Lowest progressive-validation logloss, ties broken by lowest
        point index — deterministic and seed-free."""
        key = np.where(np.isfinite(self.pv_logloss), self.pv_logloss,
                       np.inf)
        return int(np.lexsort((np.arange(len(key)), key))[0])


@_functools.lru_cache(maxsize=16)
def _ftrl_sweep_staleness_factory(mesh, K, P_pts, kernel="off"):
    """The bounded-staleness FTRL step with a ``(points,)`` lane: the
    per-point body mirrors ``_ftrl_sparse_staleness_step_factory``'s
    shard_fn OP-FOR-OP with the hyperparameters as traced per-point
    scalars (the serial program bakes python floats into the same
    arithmetic), run under a fixed-order ``jax.lax.map`` at exactly
    the serial program's shapes. Lane ``p`` matches the serial kernel
    with point ``p``'s hyperparameters to the pinned 1e-12 tolerance —
    XLA's mul->add FMA contraction is CONTEXT-dependent, so the mapped
    body rounds a last ulp differently from the standalone serial
    program on some ops (measured ~1e-17 on the f64 rig); what IS
    bitwise is population independence: a lane's result never depends
    on which other points share the sweep (same program, same lane
    shapes). One psum per chunk per point (the
    serial program's collective set, times P). ``kernel`` is the
    RESOLVED Pallas kernel-tier mode riding the lru key (the
    gather/scatter kernels are bitwise, so parity holds either way)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from ..common.compat import shard_map
    from ..engine.communication import manifest_psum
    from ..operator.stream.onlinelearning.ftrl import (_ftrl_weights,
                                                       _state_kernels)

    _sgather, _sscatter = _state_kernels(kernel)

    def shard_fn(idx, val, y, hyp, Z, N):
        # hyp: (P_pts, 4) = [alpha, beta, l1, l2] lanes; Z/N:
        # (P_pts, shard) feature-sharded per point
        shard = Z.shape[1]
        lo = jax.lax.axis_index("d") * shard
        B, w = idx.shape
        Bp = -(-B // K) * K
        if Bp != B:               # zero rows are algebraic no-ops
            idx = jnp.concatenate([idx, jnp.zeros((Bp - B, w), idx.dtype)])
            val = jnp.concatenate([val, jnp.zeros((Bp - B, w), val.dtype)])
            y = jnp.concatenate([y, jnp.zeros((Bp - B,), y.dtype)])
        xi3 = idx.reshape(Bp // K, K, w)
        xv3 = val.reshape(Bp // K, K, w)
        yy2 = y.reshape(Bp // K, K)

        def point(args):
            hp, z, n = args
            alpha, beta, l1, l2 = hp[0], hp[1], hp[2], hp[3]
            zn = jnp.stack([z, n], axis=-1)               # (shard, 2)

            def body(zn, xvy):
                xi, xv, yy = xvy
                local = (xi >= lo) & (xi < lo + shard)
                li = jnp.clip(xi - lo, 0, shard - 1)
                flat = li.reshape(-1)
                s = _sgather(zn, flat).reshape(K, w, 2)
                zj = jnp.where(local, s[..., 0], 0.0)
                nj = jnp.where(local, s[..., 1], 0.0)
                wj = jnp.where(local,
                               _ftrl_weights(zj, nj, alpha, beta, l1, l2),
                               0.0)
                margins = manifest_psum((xv * wj).sum(-1), "d",
                                        name="ftrl_margins",
                                        num_workers=mesh.size)
                p = 1.0 / (1.0 + jnp.exp(-jnp.clip(margins, -35.0, 35.0)))
                g = (p - yy)[:, None] * xv
                sigma = (jnp.sqrt(nj + g * g) - jnp.sqrt(nj)) / alpha
                dz = jnp.where(local, g - sigma * wj, 0.0)
                dn = jnp.where(local, g * g, 0.0)
                zn = _sscatter(zn, flat,
                               jnp.stack([dz.reshape(-1), dn.reshape(-1)],
                                         axis=-1))
                return zn, margins

            zn, margins = jax.lax.scan(body, zn, (xi3, xv3, yy2))
            return zn[..., 0], zn[..., 1], margins.reshape(Bp)[:B]

        Z, N, M = jax.lax.map(point, (hyp, Z, N))
        return Z, N, M

    fn = shard_map(shard_fn, mesh=mesh,
                   in_specs=(P(), P(), P(), P(), P(None, "d"),
                             P(None, "d")),
                   out_specs=(P(None, "d"), P(None, "d"), P()))
    return jax.jit(fn)


def sweep_ftrl(batches, dim: int, points, base=None, env=None,
               coef0=None) -> FtrlSweepResult:
    """Sweep N FTRL hyperparameter points (alpha/beta/l1/l2 lanes)
    through the bounded-staleness kernel as ONE program.

    ``batches``: padded-COO micro-batches ``[(idx, val, y), ...]``
    (the FTRL encode convention: (B, width) int32/float + (B,) labels,
    padding entries val == 0); ``dim``: model dimension (padded to the
    mesh); ``points``: per-point overrides over ``base`` —
    carry-resident axes alpha/beta/l1/l2 sweep inside one compiled
    program (a ``staleness`` axis whose values all RESOLVE equal keeps
    the one-program path — the compile-group base-fill semantics);
    heterogeneous ``staleness`` values record
    ``alink_sweep_fallback_total{estimator="ftrl"}`` and run the
    serial per-point STALENESS kernels instead (identical numbers,
    serial economics); an ``update_mode`` other than "staleness" is
    REFUSED loudly — this executor implements the bounded-staleness
    kernel only. ``coef0``: warm-start weights — each point's z lane
    initializes to ``-coef0 * (beta/alpha + l2)`` exactly like the
    serial drain's warm start, which is hyperparameter-DEPENDENT, so
    it must be built per point.

    Per-point results match serial
    ``_ftrl_sparse_staleness_step_factory`` drains at the pinned 1e-12
    tolerance and are BITWISE population-independent
    (tests/test_sweep.py); the winner is the lowest
    progressive-validation logloss."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..common.mlenv import MLEnvironmentFactory
    from ..kernels.ftrl import ftrl_kernel_mode
    from ..operator.stream.onlinelearning.ftrl import (
        _ftrl_sparse_staleness_step_factory)

    base = dict(base or {})
    base.setdefault("alpha", 0.1)
    base.setdefault("beta", 1.0)
    base.setdefault("l1", 0.0)
    base.setdefault("l2", 0.0)
    base.setdefault("staleness", 32)
    base.setdefault("update_mode", "staleness")
    plan = SweepPlan("ftrl", [dict(p) for p in points], base=base)
    modes = {str(p.get("update_mode", base["update_mode"]))
             for p in plan.points}
    if modes != {"staleness"}:
        # update_mode classifies as a trace axis so SweepPlan accepts
        # it, but this executor only implements the bounded-staleness
        # kernel — running a chained/per-sample point through it would
        # return silently wrong semantics. Refuse loudly instead.
        raise ValueError(
            f"sweep_ftrl sweeps the bounded-staleness kernel only; "
            f"update_mode values {sorted(modes - {'staleness'})} must "
            f"train through the serial drain (FtrlTrainStreamOp)")
    env = env or MLEnvironmentFactory.get_default()
    mesh = env.mesh
    n_dev = int(mesh.devices.size)
    dim_pad = -(-dim // n_dev) * n_dev
    K = int(base["staleness"])
    P_pts = plan.num_points
    coef0 = np.zeros(dim) if coef0 is None else np.asarray(coef0)

    def resolved(i, name):
        return float(plan.points[i].get(name, base[name]))

    hyp = np.stack([[resolved(i, "alpha"), resolved(i, "beta"),
                     resolved(i, "l1"), resolved(i, "l2")]
                    for i in range(P_pts)])

    def z0_for(i):
        # the warm start encodes the initial weights into z at n = 0 —
        # scale = beta/alpha + l2 depends on the POINT's hypers
        scale = resolved(i, "beta") / resolved(i, "alpha") \
            + resolved(i, "l2")
        z = np.zeros(dim_pad)
        z[:dim] = -coef0 * scale
        return z

    # a staleness axis only forces the serial path when its values
    # actually DIFFER: a point that names staleness explicitly but
    # equals every other point's resolved value still has ONE trace
    # group (the plan.groups() base-fill semantics) and sweeps as one
    # program — the sibling sweepers' compile-group discipline
    staleness_vals = {int(p.get("staleness", base["staleness"]))
                      for p in plan.points}
    if len(staleness_vals) == 1:
        K = staleness_vals.pop()
    else:
        record_sweep_fallback(
            "ftrl", "trace-shaping-axis",
            f"staleness values {sorted(staleness_vals)} split the scan "
            f"geometry into {len(plan.groups())} compile groups — "
            f"serial per-point kernels (identical numbers)")
        sh = NamedSharding(mesh, P("d"))
        zs, ns, ms = [], [], []
        progs = set()
        for i in range(P_pts):
            Ki = int(plan.points[i].get("staleness", base["staleness"]))
            step = _ftrl_sparse_staleness_step_factory(
                mesh, resolved(i, "alpha"), resolved(i, "beta"),
                resolved(i, "l1"), resolved(i, "l2"), Ki,
                kernel=ftrl_kernel_mode())
            progs.add((resolved(i, "alpha"), resolved(i, "beta"),
                       resolved(i, "l1"), resolved(i, "l2"), Ki))
            z = jax.device_put(z0_for(i), sh)
            n = jax.device_put(np.zeros(dim_pad), sh)
            mm = []
            for idx, val, y in batches:
                z, n, m = step(idx, val, y, z, n)
                mm.append(m)
            zs.append(np.asarray(z))
            ns.append(np.asarray(n))
            ms.append(np.concatenate([np.asarray(m) for m in mm]))
        Zh, Nh = np.stack(zs), np.stack(ns)
        Mh = np.stack(ms)
        return _finish_ftrl(plan, batches, Zh, Nh, Mh, len(progs), True)

    step = _ftrl_sweep_staleness_factory(mesh, K, P_pts,
                                         kernel=ftrl_kernel_mode())
    state_sh = NamedSharding(mesh, P(None, "d"))
    Z = jax.device_put(np.stack([z0_for(i) for i in range(P_pts)]),
                       state_sh)
    N = jax.device_put(np.zeros((P_pts, dim_pad)), state_sh)
    margins = []
    for idx, val, y in batches:
        Z, N, M = step(idx, val, y, hyp, Z, N)
        margins.append(M)
    Mh = np.concatenate([np.asarray(m) for m in margins], axis=1) \
        if margins else np.zeros((P_pts, 0))
    return _finish_ftrl(plan, batches, np.asarray(Z), np.asarray(N), Mh,
                        1, False)


def _finish_ftrl(plan, batches, Z, N, M, programs: int,
                 fallback: bool) -> FtrlSweepResult:
    y_all = (np.concatenate([y for _, _, y in batches])
             if batches else np.zeros(0))
    if M.shape[1]:
        m = np.clip(M, -35.0, 35.0)
        ll = (np.logaddexp(0.0, -m) * y_all[None, :]
              + np.logaddexp(0.0, m) * (1.0 - y_all[None, :]))
        # a non-finite margin must surface in the lane's loss, not be
        # laundered by the clip (the drain's pv_stats contract): a
        # diverged point's pv is NaN and ranks LAST in `best`
        pv = np.where(np.isfinite(M).all(axis=1), ll.mean(axis=1),
                      np.nan)
    else:
        pv = np.full(M.shape[0], np.nan)
    return FtrlSweepResult(points=plan.points, z=Z, n=N, margins=M,
                           pv_logloss=pv, programs=programs,
                           fallback=fallback)
