"""KMeans tests — mirrors the reference KMeansExample iris pipeline
(examples/KMeansExample.java:14-32) with a synthetic blob fixture."""

import numpy as np
import pytest

from alink_tpu.operator.base import TableSourceBatchOp
from alink_tpu.operator.batch.source import MemSourceBatchOp
from alink_tpu.operator.batch.clustering.kmeans_ops import (
    KMeansTrainBatchOp, KMeansPredictBatchOp, KMeansModelDataConverter)
from alink_tpu.operator.batch.evaluation import EvalClusterBatchOp
from alink_tpu.pipeline.clustering import KMeans
from alink_tpu.common import MTable, DenseVector


def _blobs(n_per=60, seed=0):
    rng = np.random.RandomState(seed)
    centers = np.asarray([[0.0, 0.0], [6.0, 6.0], [0.0, 7.0]])
    rows, labels = [], []
    for ci, c in enumerate(centers):
        pts = c + 0.4 * rng.randn(n_per, 2)
        rows += [tuple(p) for p in pts]
        labels += [ci] * n_per
    return rows, np.asarray(labels)


def test_kmeans_train_predict():
    rows, true = _blobs()
    src = MemSourceBatchOp([r + (int(t),) for r, t in zip(rows, true)],
                           "x DOUBLE, y DOUBLE, truth LONG")
    train = KMeansTrainBatchOp(k=3, feature_cols=["x", "y"], max_iter=50).link_from(src)
    pred = (KMeansPredictBatchOp(prediction_col="cluster_id",
                                 prediction_distance_col="dist")
            .link_from(train, src))
    out = pred.collect_mtable()
    ids = np.asarray(out.col("cluster_id"))
    # every true blob maps to exactly one cluster
    for t in range(3):
        assert len(set(ids[true == t])) == 1
    assert len(set(ids.tolist())) == 3
    assert np.asarray(out.col("dist")).max() < 3.0
    # converged early
    assert train._steps < 50


def test_kmeans_model_roundtrip():
    rows, _ = _blobs()
    src = MemSourceBatchOp(rows, "x DOUBLE, y DOUBLE")
    train = KMeansTrainBatchOp(k=3, feature_cols=["x", "y"]).link_from(src)
    model = KMeansModelDataConverter().load_model(train.get_output_table())
    assert model.centroids.shape == (3, 2)
    assert model.weights.sum() == pytest.approx(len(rows))
    # saved+reloaded via table round trip
    reloaded = KMeansModelDataConverter().load_model(
        MTable(train.get_output_table().to_rows(), train.get_output_table().schema))
    assert np.allclose(reloaded.centroids, model.centroids)


def test_kmeans_pipeline_and_eval():
    rows, true = _blobs()
    src = MemSourceBatchOp(rows, "x DOUBLE, y DOUBLE")
    km = KMeans(k=3, feature_cols=["x", "y"], prediction_col="cluster_id")
    model = km.fit(src)
    out = model.transform(src)
    vecs = [DenseVector([r[0], r[1]]) for r in rows]
    t2 = out.collect_mtable().add_column("vec", vecs)
    ev = (EvalClusterBatchOp(vector_col="vec", prediction_col="cluster_id")
          .link_from(TableSourceBatchOp(t2)))
    m = ev.collect_metrics()
    assert m.get("K") == 3
    assert m.get("SilhouetteCoefficient") > 0.7
    assert m.get("CalinskiHarabasz") > 100


def test_kmeans_cosine():
    rng = np.random.RandomState(1)
    a = rng.rand(50, 3) + np.asarray([5, 0, 0])
    b = rng.rand(50, 3) + np.asarray([0, 5, 0])
    rows = [tuple(r) for r in np.vstack([a, b])]
    src = MemSourceBatchOp(rows, "a DOUBLE, b DOUBLE, c DOUBLE")
    train = KMeansTrainBatchOp(k=2, feature_cols=["a", "b", "c"],
                               distance_type="COSINE").link_from(src)
    pred = KMeansPredictBatchOp(prediction_col="cid").link_from(train, src)
    ids = np.asarray(pred.collect_mtable().col("cid"))
    assert len(set(ids[:50])) == 1 and len(set(ids[50:])) == 1
    assert ids[0] != ids[50]


def test_kmeans_parallel_init_quality_parity():
    """K-MEANS|| seeding must match host kmeans++ quality (VERDICT item 5):
    final Lloyd cost ratio within 10% on a blob mixture."""
    from alink_tpu.operator.common.clustering.kmeans import kmeans_train

    rng = np.random.RandomState(0)
    k, d = 12, 6
    centers = rng.randn(k, d) * 8
    X = np.concatenate([c + rng.randn(400, d) for c in centers]).astype(np.float32)

    def final_cost(init):
        C, _, _ = kmeans_train(X, k=k, max_iter=30, tol=1e-5, init=init, seed=1)
        d2 = ((X[:, None, :] - C[None, :, :]) ** 2).sum(-1).min(1)
        return float(d2.sum())

    c_par = final_cost("K_MEANS_PARALLEL")
    c_pp = final_cost("K_MEANS_PLUS_PLUS")
    assert c_par <= c_pp * 1.10, (c_par, c_pp)


def test_kmeans_parallel_init_no_host_pass():
    """k=100 on 400k sharded rows: the seeding itself runs as one BSP
    program; only the O(rounds*oversample) candidate set reaches the host."""
    from alink_tpu.operator.common.clustering.kmeans import (
        kmeans_parallel_init)

    rng = np.random.RandomState(1)
    k = 100
    X = rng.randn(400_000, 8).astype(np.float32) * 3
    C = kmeans_parallel_init(X, k, seed=0)
    assert C.shape == (k, 8)
    assert np.isfinite(C).all()
    # seeds cover the data: every centroid is near some data region and
    # centroids are mutually distinct
    pd = ((C[:, None, :] - C[None, :, :]) ** 2).sum(-1)
    np.fill_diagonal(pd, np.inf)
    assert (pd.min(1) > 1e-6).all()
