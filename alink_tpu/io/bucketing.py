"""Bucketing row sink.

Re-design of the reference's ``TableBucketingSink``
(common/io/TableBucketingSink.java:23-160): a row sink that routes incoming
rows into rolling numbered bucket tables ``<prefix>_<id>``. Two modes,
selected exactly as the reference selects them:

- **ruler mode** (``batch_size < 0`` and ``batch_rollover_interval < 0``):
  each row carries its bucket id and the bucket's total row count as the
  first two fields ``(id, n_tab, *payload)``; a bucket closes once its
  count is reached (TableBucketingSink.java:63-81 ``writeByRuler``).
- **size-or-time mode**: rows go to the current bucket ``currentId``,
  which rolls over to a fresh bucket after ``batch_size`` rows or
  ``batch_rollover_interval`` seconds (writeBySizeOrTime, :123-135). As in
  the reference, setting only one bound leaves the other unbounded
  (TableBucketingSink.java:44-51).

Buckets land either in a ``BaseDB`` (table per bucket, like the
reference's ``db.createFormat``) or in a partitioned directory of CSV
files ``<dir>/<prefix>_<id>.csv`` — the file-system analogue for the
TPU build, where downstream per-host sharded readers (io/sharding.py)
consume one bucket file per shard.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

from ..common.mtable import MTable
from ..common.types import TableSchema
from .csv import format_csv_rows, write_csv
from .db import BaseDB


class TableBucketingSink:
    """Row sink that rolls output into numbered bucket tables
    ``<prefix>_<id>`` — in a BaseDB or as a partitioned directory of CSV
    files — by explicit bucket-id columns (ruler mode) or by
    size/time rollover (reference common/io/TableBucketingSink.java).

    Pre-existing bucket targets: in **ruler mode** a bucket that already
    exists is an error (the ruler's ``(id, n_tab)`` contract says this
    process owns the bucket's full row count — TableBucketingSink.java:
    94-95). In **size/time mode** the reference REUSES an existing table
    and appends to it (createFormat is only consulted for new tables), so
    this sink tolerates existing targets and appends.

    Unit note: the reference's ``batchRolloverInterval`` is milliseconds
    (a Flink config long); here ``batch_rollover_interval`` is **seconds**
    (a float, matching every other time knob in this codebase — stream
    ``time_interval``, event times). Divide reference configs by 1000.
    """

    def __init__(self, table_name_prefix: str, schema: TableSchema,
                 db: Optional[BaseDB] = None, base_dir: Optional[str] = None,
                 batch_size: int = -1, batch_rollover_interval: float = -1.0,
                 clock=time.monotonic):
        if (db is None) == (base_dir is None):
            raise ValueError("pass exactly one of db= or base_dir=")
        self.prefix = table_name_prefix
        self.schema = schema
        self.db = db
        self.base_dir = base_dir
        # mode is fixed at construction (before the one-sided widening
        # below makes both bounds positive)
        self._ruler = batch_size < 0 and batch_rollover_interval < 0
        # one-sided bounds widen the other side (TableBucketingSink.java:44-51)
        if batch_size > 0 and batch_rollover_interval < 0:
            batch_rollover_interval = float("inf")
        if batch_size < 0 and batch_rollover_interval > 0:
            batch_size = 2 ** 62
        self.batch_size = batch_size
        self.batch_rollover_interval = batch_rollover_interval
        self._clock = clock
        self._start_time = clock()
        self._current_id = 0
        # bucket id -> (rows written so far, buffered rows)
        self._open: Dict[int, Tuple[int, List[tuple]]] = {}

    # -- public sink surface -------------------------------------------------
    def invoke(self, row: tuple) -> None:
        """Write one row (reference ``invoke``, TableBucketingSink.java:55-61)."""
        if self.batch_size < 0 and self.batch_rollover_interval < 0:
            self._write_by_ruler(row)
        else:
            self._write_by_size_or_time(row)

    def write_table(self, mt: MTable) -> None:
        """Convenience: feed every row of a table (micro-batch drain)."""
        for row in mt.to_rows():
            self.invoke(row)

    def close(self) -> None:
        """Flush any buckets still open (end of stream)."""
        for bucket_id in list(self._open):
            self._close_bucket(bucket_id)

    def bucket_names(self) -> List[str]:
        """Names of all buckets written so far (closed or open)."""
        def bucket_id(name: str):
            tail = name.rsplit("_", 1)[1]
            return (0, int(tail)) if tail.isdigit() else (1, tail)

        if self.db is not None:
            return sorted((t for t in self.db.list_table_names()
                           if t.startswith(self.prefix + "_")), key=bucket_id)
        if not os.path.isdir(self.base_dir):
            return []
        return sorted((os.path.splitext(f)[0] for f in os.listdir(self.base_dir)
                       if f.startswith(self.prefix + "_")), key=bucket_id)

    # -- modes ---------------------------------------------------------------
    def _write_by_ruler(self, row: tuple) -> None:
        bucket_id, n_tab = int(row[0]), int(row[1])
        payload = tuple(row[2:])
        count, buf = self._open.get(bucket_id, (0, None))
        if buf is None:
            self._create_bucket(bucket_id)
            buf = []
        buf.append(payload)
        count += 1
        self._open[bucket_id] = (count, buf)
        if count == n_tab:
            self._close_bucket(bucket_id)

    def _write_by_size_or_time(self, row: tuple) -> None:
        bucket_id = self._current_id
        count, buf = self._open.get(bucket_id, (0, None))
        if buf is None:
            self._create_bucket(bucket_id)
            buf = []
        buf.append(tuple(row))
        count += 1
        self._open[bucket_id] = (count, buf)
        if (count >= self.batch_size or
                self._clock() - self._start_time > self.batch_rollover_interval):
            self._close_bucket(bucket_id)
            self._start_time = self._clock()
            self._current_id += 1

    # -- bucket lifecycle ----------------------------------------------------
    def _bucket_name(self, bucket_id: int) -> str:
        return f"{self.prefix}_{bucket_id}"

    def _create_bucket(self, bucket_id: int) -> None:
        name = self._bucket_name(bucket_id)
        if self.db is not None:
            if self.db.has_table(name):
                if self._ruler:
                    # same contract as TableBucketingSink.java:94-95 —
                    # ruler mode only; size/time mode reuses the table
                    raise RuntimeError(f"table : {name} has already exists, "
                                       f"please change your table name.")
                return
            self.db.create_table(name, self.schema)
        else:
            os.makedirs(self.base_dir, exist_ok=True)
            path = os.path.join(self.base_dir, name + ".csv")
            if os.path.exists(path) and self._ruler:
                raise RuntimeError(f"table : {name} has already exists, "
                                   f"please change your table name.")

    def _close_bucket(self, bucket_id: int) -> None:
        count, buf = self._open.pop(bucket_id)
        mt = MTable(buf, self.schema)
        name = self._bucket_name(bucket_id)
        if self.db is not None:
            self.db.write_table(name, mt, append=True)
        else:
            path = os.path.join(self.base_dir, name + ".csv")
            if not self._ruler and os.path.exists(path):
                # size/time mode reuses a pre-existing bucket file by
                # appending, mirroring the db branch's append=True
                with open(path, "a", newline="", encoding="utf-8") as f:
                    f.write(format_csv_rows(mt))
            else:
                write_csv(mt, path)
