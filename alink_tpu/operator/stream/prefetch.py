"""Bounded prefetch for stream drains — host/device pipelining.

The Flink reference runs every stream operator as its own pipelined task:
while FtrlTrainStreamOp's CalcTask crunches batch t, the upstream hash /
parse operators are already producing batch t+1
(FtrlTrainStreamOp.java:120-135). The round-2 runtime was a single lazy
generator chain, so host encode and device compute ran strictly
back-to-back (VERDICT r2 #4).

``prefetch(it, depth)`` runs the upstream iterator in ONE background
thread feeding a bounded channel: the main thread dispatches device steps
for item t while the thread parses/hashes/pads item t+1. FIFO order is
preserved exactly (test_stream.py proves no reordering), the bound gives
backpressure (the thread blocks when the consumer falls behind — Flink's
bounded exchange buffers), and upstream exceptions re-raise at the
consumption point. Per-sample order INSIDE a batch is untouched, so
strict-FTRL semantics are unchanged.

``prefetch_map(it, fn, workers=N)`` is the multi-worker upgrade: ``fn``
(the parse/hash/encode work) runs on an ORDERED pool of ``N`` named
threads (``alink-prefetch-<i>``) while the upstream iterator itself is
still drained serially — results are emitted in exact input order via a
reordering buffer, so callers observe the single-thread contract at
N-fold host parallelism. Exceptions (from ``fn`` or the upstream) are
delivered at the position where the failing item would have been
yielded, never earlier.

Backpressure is stop-aware: producers wait on a condition variable, not
a poll loop, so a consumer that abandons the stream (STOP sentinel
downstream, an exception) wakes every blocked producer immediately.

Env knobs:
  * ``ALINK_TPU_STREAM_PREFETCH`` — depth override; "0" disables
    (inline iteration), unset means depth 2.
  * ``ALINK_TPU_STREAM_WORKERS`` — pool width for :func:`prefetch_map`
    callers that pass ``workers=None``; unset/1 keeps the single-thread
    path.

Observability: the channel exports an ``alink_prefetch_depth`` gauge
(items currently buffered, labelled by consumer) so a stalled producer
(gauge pinned at 0) or a stalled consumer (pinned at the bound) is
visible in ``tools/run_report.py`` output.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Iterable, Iterator, Optional, TypeVar

from ...common.faults import maybe_crash

T = TypeVar("T")
U = TypeVar("U")

_SENTINEL = object()
# timed-get miss marker (serving micro-batcher): distinct from the
# end-of-stream sentinel so "nothing arrived within the latency budget"
# and "the stream is over" stay distinguishable
_EMPTY = object()


def prefetch_depth(default: int = 2) -> int:
    """``ALINK_TPU_STREAM_PREFETCH`` via the flag registry
    (common/flags.py): set-but-empty counts as unset, values clamp to
    >= 0 — the historical semantics, one parser."""
    from ...common.flags import flag_value
    return flag_value("ALINK_TPU_STREAM_PREFETCH", default)


def stream_workers(default: int = 1) -> int:
    """``ALINK_TPU_STREAM_WORKERS``: width of the :func:`prefetch_map`
    encode pool (registry-declared; clamps to >= 1). 1 (the default)
    is the exact single-thread behavior."""
    from ...common.flags import flag_value
    return flag_value("ALINK_TPU_STREAM_WORKERS", default)


class _Channel:
    """Bounded FIFO channel with stop-aware blocking.

    ``put`` blocks while the channel is full — but wakes IMMEDIATELY when
    the consumer abandons the stream (``stop()``), instead of the old
    0.1 s ``queue.Full`` poll loop. ``get`` blocks until an item or the
    sentinel arrives. One lock + two conditions; unbounded when
    ``maxsize <= 0``."""

    def __init__(self, maxsize: int, gauge_label: Optional[str] = None):
        self._buf: deque = deque()
        self._maxsize = maxsize
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._not_empty = threading.Condition(self._lock)
        self._stopped = False
        self._closed = False
        self._gauge_label = gauge_label

    def _gauge(self, depth: int) -> None:
        if self._gauge_label is None:
            return
        from ...common.metrics import get_registry, metrics_enabled
        if metrics_enabled():
            get_registry().set_gauge("alink_prefetch_depth", depth,
                                     {"consumer": self._gauge_label})

    def put(self, item) -> bool:
        """Enqueue; False when the consumer has stopped OR the channel
        is already closed (a producer racing ``close()`` must not
        strand an item no getter will ever see — the serving tier's
        submit-vs-shutdown race)."""
        with self._not_full:
            while not self._stopped and not self._closed \
                    and self._maxsize > 0 \
                    and len(self._buf) >= self._maxsize:
                self._not_full.wait()
            if self._stopped or self._closed:
                return False
            self._buf.append(item)
            self._gauge(len(self._buf))
            self._not_empty.notify()
            return True

    def get(self, timeout: Optional[float] = None):
        """Dequeue one item; blocks until an item, stop/close
        (``_SENTINEL``) or — when ``timeout`` is given — the deadline
        (``_EMPTY``). ``timeout=None`` is the historical behavior;
        ``timeout=0`` polls without blocking (the micro-batcher's
        "queue already holds a full batch" fast path)."""
        # deterministic fault site (common/faults.py): every consumer —
        # stream drains AND the serving micro-batcher — pulls through
        # here, so an error-mode fault is a consumer-loop crash (the
        # serving supervisor's respawn path) and delay:MS injects
        # upstream latency. Unarmed cost: one os.environ probe
        maybe_crash("prefetch.get")
        deadline = None if timeout is None \
            else time.monotonic() + max(0.0, timeout)
        with self._not_empty:
            while not self._buf:
                if self._stopped or self._closed:
                    return _SENTINEL
                if deadline is None:
                    self._not_empty.wait()
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return _EMPTY
                self._not_empty.wait(remaining)
            item = self._buf.popleft()
            self._gauge(len(self._buf))
            self._not_full.notify()
            return item

    def depth(self) -> int:
        """Items currently buffered (the admission-control reading the
        serving tier exports as ``alink_serve_queue_depth``)."""
        with self._lock:
            return len(self._buf)

    def drain(self, max_items: int) -> list:
        """Pop up to ``max_items`` buffered items under ONE lock
        acquisition (never blocks; [] when empty). The serving
        micro-batcher's bulk path — a per-item ``get`` would pay a
        lock round trip per coalesced request."""
        with self._lock:
            k = min(int(max_items), len(self._buf))
            if k <= 0:
                return []
            items = [self._buf.popleft() for _ in range(k)]
            self._gauge(len(self._buf))
            self._not_full.notify_all()
            return items

    def close(self) -> None:
        """Producer end-of-stream: buffered items still DRAIN to getters;
        once empty, every get() returns the sentinel (non-consuming, so
        any number of pool workers observe it)."""
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()   # blocked producers must re-check

    def stop(self) -> None:
        """Consumer abandonment: wake every blocked producer AND consumer
        at once (no poll latency), discard buffered items."""
        with self._lock:
            self._stopped = True
            self._buf.clear()
            self._gauge(0)
            self._not_full.notify_all()
            self._not_empty.notify_all()


def _close_upstream(it, err: list) -> None:
    """Close the upstream generator on EVERY producer exit path (normal
    end, upstream error, consumer abandonment) so a failing
    flush-on-close still reaches the consumer instead of dying on the
    daemon thread."""
    try:
        close = getattr(it, "close", None)
        if close is not None:
            close()
    except BaseException as e:
        err.append(e)


def _warn_stuck(threads, timeout: float = 5.0) -> None:
    """Join ``threads`` against ONE shared deadline (not 5 s each — a
    blocked 8-wide pool would otherwise stall an abandoning consumer
    ~45 s). A thread still alive past the deadline is stuck inside the
    upstream iterator / fn itself (e.g. a blocking poll) — it cannot see
    the stop flag until that call returns, so the daemon thread outlives
    us still holding the iterator. Make that diagnosable, not silent."""
    deadline = time.monotonic() + timeout
    for th in threads:
        th.join(timeout=max(0.0, deadline - time.monotonic()))
    stuck = [th.name for th in threads if th.is_alive()]
    if stuck:
        import logging
        logging.getLogger(__name__).warning(
            "prefetch worker(s) %s did not exit within %.0fs of consumer "
            "abandonment; the upstream source appears blocked",
            ", ".join(stuck), timeout)


def prefetch(it: Iterable[T], depth: int = None,
             name: str = None) -> Iterator[T]:
    """Iterate ``it`` in a background thread, ``depth`` items ahead.

    ``name`` labels this channel's ``alink_prefetch_depth`` gauge
    (``consumer=<name>``); pass the consuming op's name so concurrent
    streams do not overwrite each other's depth reading."""
    depth = prefetch_depth() if depth is None else depth
    if depth <= 0:
        yield from it
        return
    ch = _Channel(depth, gauge_label=name or "prefetch")
    err: list = []

    def worker():
        try:
            for item in it:
                if not ch.put((item,)):
                    break
        except BaseException as e:  # propagate to the consumer
            err.append(e)
        finally:
            _close_upstream(it, err)
            ch.put(_SENTINEL)

    th = threading.Thread(target=worker, daemon=True,
                          name="alink-prefetch-0")
    th.start()
    try:
        while True:
            item = ch.get()
            if item is _SENTINEL:
                if err:
                    raise err[0]
                return
            yield item[0]
    finally:
        # consumer abandoned early (STOP sentinel downstream, exception):
        # stop() wakes an in-flight put immediately — no drain loop needed
        ch.stop()
        _warn_stuck([th])


def prefetch_map(it: Iterable[T], fn: Callable[[T], U],
                 workers: int = None, depth: int = None,
                 name: str = None) -> Iterator[U]:
    """Ordered parallel map: ``fn(item)`` for every item of ``it``, on a
    pool of ``workers`` threads, yielding results in EXACT input order.

    The upstream iterator is drained serially by a dispatcher thread
    (generators are inherently sequential); the per-item work in ``fn``
    — parse/hash/encode/device_put for the stream runtime — is what
    parallelizes. A reordering buffer holds at most
    ``workers + depth`` completed results, so memory stays bounded by
    the same backpressure contract as :func:`prefetch`.

    ``workers=None`` reads ``ALINK_TPU_STREAM_WORKERS`` (default 1);
    ``workers=1`` degrades to :func:`prefetch` over a lazy ``map`` —
    byte-for-byte the single-thread behavior. An exception raised by
    ``fn(item_k)`` (or by the upstream while producing item k) re-raises
    at the consumer exactly where item k would have been yielded; items
    ``< k`` are still delivered first."""
    workers = stream_workers() if workers is None else max(1, int(workers))
    depth = prefetch_depth() if depth is None else depth
    if workers <= 1:
        # a real generator, not map(): closing it must deterministically
        # close the UPSTREAM too (map objects have no close(), which
        # would silently defeat _close_upstream's flush-on-close
        # propagation — the contract the single-thread path always had)
        def _mapped():
            try:
                for item in it:
                    yield fn(item)
            finally:
                close = getattr(it, "close", None)
                if close is not None:
                    close()
        yield from prefetch(_mapped(), depth=depth, name=name)
        return

    in_ch = _Channel(max(depth, 1),
                     gauge_label=(name or "prefetch_map") + ".in")
    lock = threading.Lock()
    done = threading.Condition(lock)
    results: dict = {}          # seq -> ("ok", value) | ("err", exc)
    state = {"stop": False, "total": None}  # total set once upstream ends

    def dispatcher():
        seq = 0
        try:
            for item in it:
                if not in_ch.put((seq, item)):
                    return
                seq += 1
        except BaseException as e:
            # the upstream failed while producing item `seq`: deliver the
            # error at that position, after every earlier item
            with done:
                results[seq] = ("err", e)
                seq += 1
                done.notify_all()
        finally:
            err2: list = []
            _close_upstream(it, err2)
            with done:
                if err2 and seq not in results:
                    results[seq] = ("err", err2[0])
                    seq += 1
                state["total"] = seq
                done.notify_all()
            # close, not stop: queued items must still reach the workers
            in_ch.close()

    bound = workers + max(depth, 1)

    def worker():
        while True:
            with done:
                # admission control, not storage control: a worker only
                # PULLS new work while the reorder buffer has room, but
                # always stores what it finished — gating the store
                # would deadlock when the buffer fills with seqs ahead
                # of the one the consumer is waiting for
                while not state["stop"] and len(results) >= bound:
                    done.wait()
                if state["stop"]:
                    return
            got = in_ch.get()
            if got is _SENTINEL:
                return
            seq, item = got
            try:
                out = ("ok", fn(item))
            except BaseException as e:
                out = ("err", e)
            with done:
                if state["stop"]:
                    return
                results[seq] = out
                done.notify_all()

    threads = [threading.Thread(target=dispatcher, daemon=True,
                                name="alink-prefetch-dispatch")]
    threads += [threading.Thread(target=worker, daemon=True,
                                 name=f"alink-prefetch-{i}")
                for i in range(workers)]
    for th in threads:
        th.start()
    next_seq = 0
    try:
        while True:
            with done:
                while next_seq not in results:
                    if state["total"] is not None \
                            and next_seq >= state["total"]:
                        return
                    done.wait()
                kind, val = results.pop(next_seq)
                done.notify_all()     # admission-gated workers wake here
            if kind == "err":
                raise val
            yield val
            next_seq += 1
    finally:
        with done:
            state["stop"] = True
            results.clear()
            done.notify_all()
        in_ch.stop()
        _warn_stuck(threads)
