"""IterativeComQueue — the BSP iterative-compute engine, TPU-native.

Re-design of the reference's ComQueue framework
(common/comqueue/BaseComQueue.java:154-308 ``exec``; IterativeComQueue.java:6):

reference mechanism                      ->  TPU-native mechanism
----------------------------------------     ------------------------------------
Flink IterativeDataSet superstep loop        ``lax.while_loop`` body (one jit)
ComputeFunction.calc(ComContext)             pure stage fn over a carry pytree
AllReduce 3-phase shuffle                    ``lax.psum`` over mesh axis 'd'
partition data cached in TM heap             device-resident sharded arrays
  (SessionSharedObjs.java:157-178)             closed over by the jitted step
withBroadcastSet replication                 replicated (unsharded) arrays
stop-criterion on node 0 + rebroadcast       criterion fn -> ``__stop`` carry bit
  (BaseComQueue.java:242-304)                  (computed on replicated state)
CompleteResultFunction on final state        ``close_with`` host callback

The whole superstep loop — all stages plus collectives — compiles to ONE XLA
program via ``shard_map`` over the session mesh; Flink's per-superstep
scheduling overhead has no analogue. Stage chaining (``optimize()``,
BaseComQueue.java:470-495) is subsumed by XLA fusion.

Contract notes:
  * Partitioned arrays are zero-padded along axis 0 to a multiple of the
    worker count. Algorithms must carry an explicit per-sample weight/mask
    column if padding can perturb them (the reference's Tuple3(weight, ...)
    training format already does this).
  * Stage allocations (reference ``stepNo == 1`` idiom) must happen when
    ``context.is_init_step`` is True; the carry structure is frozen after
    the first superstep.
"""

from __future__ import annotations

import time
import warnings
import weakref
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..common.health import health_enabled
from ..common.mlenv import MLEnvironment, MLEnvironmentFactory
from ..common.profiling2 import (hbm_snapshot, mark as profile_mark,
                                 profile_enabled, profile_window)
from ..common.tracing import trace_instant, trace_span, tracing_enabled
from .context import ComContext
from .communication import CommunicateFunction

# Compiled-program cache across exec() calls. Every exec() used to build
# a fresh ``run`` closure, so jax.jit could never hit its own cache and
# every fit paid the full trace+compile (~10-18 s for the optimizer
# programs) even when an identical program had just run. The reference
# pays plan-construction per exec too, but its plan build is cheap
# (BaseComQueue.java:154-308); execution cost is per run. Here the
# expensive artifact is the compiled XLA program, so it is cached keyed
# on (caller program_key, mesh, worker count, max_iter, seed,
# criterion-presence, input-name sets). Shape/dtype polymorphism is
# handled by jax.jit itself underneath each entry.
#
# Caller contract for ``program_key``: the key must determine the stage
# list's STRUCTURE and every Python-level constant the stage closures
# bake into the trace (hyperparameters, dims, loss config). Training
# DATA always flows through partitioned/broadcast inputs, never through
# the key — a cached program re-runs correctly on fresh data.
_PROGRAM_CACHE: "OrderedDict[tuple, Callable]" = OrderedDict()
_PROGRAM_CACHE_MAX = 32
_PROGRAM_CACHE_STATS = {"hits": 0, "misses": 0}
# jaxpr text per cached key, populated only under ALINK_VERIFY_PROGRAM_CACHE
_PROGRAM_CACHE_JAXPRS: Dict[tuple, str] = {}
# per-superstep collective manifest per cached key (communication.collecting
# capture, recorded at trace time): {"init": [...], "body": [...]} of
# (kind, buffer, logical_bytes) triples. Kept OUTSIDE the metrics guard so
# a program compiled under ALINK_TPU_METRICS=0 still carries its manifest
# when a later metrics-on exec hits the cache.
_PROGRAM_CACHE_MANIFESTS: Dict[tuple, dict] = {}
# XLA static cost model per cached key (compat.compiled_cost_analysis on
# the lowered program). Computed lazily and only under ALINK_TPU_TRACE —
# the lowering costs a full re-trace, so the default path never pays it.
_PROGRAM_CACHE_COSTS: Dict[tuple, dict] = {}

# Engine phase wall-clock (prepare inputs / execute+compile / collect).
# Spans mirror into the MetricsRegistry as alink_step_timer_seconds via
# StepTimer itself, so one registry dump carries engine timing too.
from ..common.profiling import StepTimer as _StepTimer

_ENGINE_TIMER = _StepTimer()


def engine_timer():
    """The engine-phase StepTimer (host wall-clock per exec phase)."""
    return _ENGINE_TIMER


def program_cache_stats() -> Dict[str, int]:
    """Cumulative hit/miss counters (observability + tests)."""
    return dict(_PROGRAM_CACHE_STATS)


def donation_enabled() -> bool:
    """``ALINK_TPU_DONATE`` (default ON): donate the chunk-loop carry into
    the compiled ``cont`` chunk program (``jax.jit(donate_argnums=...)``).
    XLA then aliases the carry's input buffers to the output buffers —
    the per-chunk copy-on-entry disappears and the carry's HBM working
    set halves for large models (the reference mutates its shared model
    state in place, SessionSharedObjs; donation is the compiled-loop
    analogue). Read live and folded into the program-cache key, so
    toggling it recompiles instead of serving a structurally different
    cached program.

    Only the ``cont`` program has a carry INPUT to donate: the single
    and first-chunk programs construct the carry inside the trace (the
    init pass), so there is nothing to alias — the flag is a no-op for
    them beyond the cache-key fold. Donation contract for callers: a
    buffer passed into a donated argument is dead after the call
    (``RuntimeError: Array has been deleted`` on reuse) — fetch anything
    you still need BEFORE re-entering the program
    (docs/performance.md)."""
    from ..common.metrics import env_flag
    return env_flag("ALINK_TPU_DONATE", default=True)


def clear_program_cache() -> None:
    _PROGRAM_CACHE.clear()
    _PROGRAM_CACHE_JAXPRS.clear()
    _PROGRAM_CACHE_MANIFESTS.clear()
    _PROGRAM_CACHE_COSTS.clear()


def _program_label(program_key) -> str:
    """Human-readable, bounded-cardinality label for per-program metrics.
    Callers conventionally lead their ``set_program_key`` tuple with a
    short algorithm string (``("qn", ...)``, ``("als", ...)``); fall back
    to a digest when the key has no such prefix."""
    if isinstance(program_key, (tuple, list)) and program_key \
            and isinstance(program_key[0], str):
        return program_key[0]
    import hashlib
    return hashlib.blake2b(repr(program_key).encode(),
                           digest_size=6).hexdigest()


class _AotMeshCall:
    """Dispatch a deserialized engine program (ISSUE 20).  An exported
    multi-device module must be called in a context with the device
    count it was built for, so each positional argument's leaves are
    placed onto the exec mesh first — ``shard`` along the worker axis
    (parts, stacked carries), ``repl`` replicated (broadcast state,
    loop limits).  Single-device meshes skip placement; ``lower``
    delegates so the static-cost probe keeps working."""

    __slots__ = ("_fn", "_mesh", "_specs")

    def __init__(self, fn: Callable, mesh, specs: Sequence[str]):
        self._fn = fn
        self._mesh = mesh
        self._specs = tuple(specs)

    def __call__(self, *args):
        import jax
        mesh = self._mesh
        if mesh is not None and int(np.prod(mesh.devices.shape)) > 1:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as _P
            sh = {"shard": NamedSharding(mesh, _P("d")),
                  "repl": NamedSharding(mesh, _P())}
            args = tuple(
                jax.tree_util.tree_map(
                    lambda x, _s=sh[spec]: jax.device_put(x, _s), a)
                for a, spec in zip(args, self._specs))
        return self._fn(*args)

    def lower(self, *args, **kwargs):
        return self._fn.lower(*args, **kwargs)


def _maybe_cost(ckey: Optional[tuple], lower_thunk: Callable) -> Optional[dict]:
    """The cached program's static XLA cost dict, memoized per key.

    Computed only under ``ALINK_TPU_TRACE`` (``lower_thunk`` re-traces the
    program, seconds for the big optimizer programs); once computed it is
    served from the memo so later traced execs pay a dict lookup. An
    unavailable cost model memoizes as ``{}`` — degraded jax versions must
    not re-pay the lowering on every traced exec just to learn None
    again."""
    if ckey is None:
        return None
    cost = _PROGRAM_CACHE_COSTS.get(ckey)
    if cost is None and tracing_enabled():
        from ..common.compat import compiled_cost_analysis
        cost = compiled_cost_analysis(lower_thunk()) or {}
        _PROGRAM_CACHE_COSTS[ckey] = cost
    return cost or None


def freeze_config(v):
    """Hashable token of a config object for ``set_program_key``. Captures
    every Python constant stage closures bake into a trace (loss type,
    dims, regularization, field metadata). Arrays hash by content; objects
    by public attrs, recursively."""
    import dataclasses
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return v
    if isinstance(v, (tuple, list)):
        return tuple(freeze_config(x) for x in v)
    if isinstance(v, dict):
        # sort by (type, repr) so mixed-type keys (int and str) still
        # produce a stable key instead of raising from sorted()
        return tuple(sorted(((k, freeze_config(x)) for k, x in v.items()),
                            key=lambda kv: (type(kv[0]).__name__, repr(kv[0]))))
    if isinstance(v, np.ndarray) or (hasattr(v, "shape") and hasattr(v, "dtype")):
        a = np.asarray(v)
        raw = a.tobytes()
        if len(raw) > 512:
            # digest large arrays: raw bytes in the key would copy MBs per
            # fit and pin them in the LRU
            import hashlib
            raw = hashlib.blake2b(raw, digest_size=16).digest()
        return ("nd", a.shape, str(a.dtype), raw)
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return (type(v).__name__, freeze_config(dataclasses.asdict(v)))
    if hasattr(v, "__dict__"):
        # PUBLIC attrs only: a config object must not hide trace-relevant
        # state in underscore attrs (the set_program_key contract)
        return (type(v).__name__,
                tuple(sorted((k, freeze_config(x)) for k, x in vars(v).items()
                             if not k.startswith("_"))))
    # no safe generic fallback: default repr() embeds the memory address,
    # which would make the key never match (a silent permanent cache miss
    # churning the LRU) — force the caller to pass something freezable
    raise TypeError(f"freeze_config: cannot build a stable key from "
                    f"{type(v).__name__!r}; pass scalars, arrays, "
                    f"dataclasses, or objects with public __dict__ attrs")


def _freeze_closure_value(v, depth):
    """Best-effort hashable token of one closure-cell value for the
    program-cache structural guard. Unlike freeze_config this must be
    TOTAL (never raise) and must NOT fetch device arrays to host — so it
    recurses itself instead of delegating containers to freeze_config."""
    import dataclasses
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return v
    if isinstance(v, np.ndarray):  # host memory: content hash is cheap
        if v.nbytes > 512:
            import hashlib
            # hash the buffer in place — tobytes() would copy the whole
            # array on every exec() including cache hits
            buf = v.data if v.flags.c_contiguous else \
                np.ascontiguousarray(v).data
            raw = hashlib.blake2b(buf, digest_size=16).digest()
        else:
            raw = v.tobytes()
        return ("nd", v.shape, str(v.dtype), raw)
    if isinstance(v, type):  # a CLASS in a cell (e.g. a slotted type whose
        # 'shape' attr is a member_descriptor, not a value). getattr with
        # defaults: pybind11-defined classes (old jaxlib's PmapFunction)
        # can lack __module__/__qualname__, and this function must be TOTAL
        return ("type", getattr(v, "__module__", "?"),
                getattr(v, "__qualname__", getattr(v, "__name__", repr(v))))
    if hasattr(v, "shape") and hasattr(v, "dtype"):
        # jax.Array: data belongs in partitioned/broadcast inputs by
        # contract; hashing its CONTENT would round-trip device memory.
        # Shape/dtype suffices to catch structural drift.
        try:
            return ("devarray", tuple(v.shape), str(v.dtype))
        except TypeError:
            return ("opaque", type(v).__module__, type(v).__qualname__)
    # containers decrement depth too: a cyclic container (cfg['self'] =
    # cfg) must degrade to an opaque token, not overflow the stack
    if isinstance(v, (tuple, list)):
        if depth <= 0:
            return ("opaque", type(v).__name__, len(v))
        return tuple(_freeze_closure_value(x, depth - 1) for x in v)
    if isinstance(v, dict):
        if depth <= 0:
            return ("opaque", "dict", len(v))
        return tuple(sorted(
            ((repr(k), _freeze_closure_value(x, depth - 1))
             for k, x in v.items())))
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        if depth <= 0:
            return ("opaque", type(v).__name__)
        return (type(v).__name__, tuple(
            (f.name, _freeze_closure_value(getattr(v, f.name), depth - 1))
            for f in dataclasses.fields(v)))
    if callable(v) and depth > 0:
        return _callable_digest(v, depth - 1)
    if hasattr(v, "__dict__") and depth > 0:  # depth bounds cyclic graphs
        return (type(v).__name__, tuple(sorted(
            (k, _freeze_closure_value(x, depth - 1))
            for k, x in vars(v).items() if not k.startswith("_"))))
    return ("opaque", getattr(type(v), "__module__", "?"),
            getattr(type(v), "__qualname__", type(v).__name__))


# dedup keys for the devarray-in-closure warning below: one warning per
# (stage, cell) pair — per-exec repeats would be noise, but a SECOND
# offending stage (or a second cell of the same stage) is a distinct
# bug and must not be muted by the first (the historical once-per-
# process flag did exactly that). Runtime twin of the alink-lint
# TRACED-CAPTURE rule, so the two diagnostics agree on name and unit.
_DEVARRAY_CELL_WARNED: set = set()


def _contains_devarray(v, depth=3) -> bool:
    """True when a closure-cell value holds a jax.Array (directly or
    nested in a shallow container). The check is a POSITIVE isinstance
    against jax.Array — duck-typing on shape/dtype would also trip on
    numpy scalars, pandas Series, or ShapeDtypeStructs, and a false
    positive here both misleads the user and burns the once-per-process
    warning before a genuine device-array capture can use it."""
    if v is None or isinstance(v, (bool, int, float, str, bytes, type,
                                   np.ndarray, np.generic)):
        return False
    try:
        import jax
        if isinstance(v, jax.Array):
            return True
    except (ImportError, AttributeError):  # pragma: no cover - old jax
        if isinstance(getattr(v, "shape", None), tuple) \
                and hasattr(v, "dtype") \
                and type(v).__module__.split(".")[0] in ("jax", "jaxlib"):
            return True
    if depth <= 0:
        return False
    if isinstance(v, (tuple, list)):
        return any(_contains_devarray(x, depth - 1) for x in v)
    if isinstance(v, dict):
        return any(_contains_devarray(x, depth - 1) for x in v.values())
    return False


def _warn_devarray_cell(fn_name: str, cell_name: str, key=None) -> None:
    """The structural cache guard tokenizes device arrays by shape/dtype
    ONLY (hashing content would round-trip device memory per exec), so a
    stage closure holding a jax.Array whose CONTENT changes between
    execs would silently re-run the stale cached program — the content
    is baked into the trace as a constant (ADVICE round 5,
    comqueue.py:144). Warn once per (stage, cell): data belongs in
    partitioned/broadcast inputs, not closures. This is the runtime
    twin of the static TRACED-CAPTURE rule (``python -m tools.lint``) —
    same rule name, same per-(stage, cell) unit. ``key`` carries the
    caller's dedup identity (module + qualname): two DISTINCT defs that
    merely share a nested name like ``step`` are two distinct bugs and
    must both warn."""
    key = key or (fn_name, cell_name)
    if key in _DEVARRAY_CELL_WARNED:
        return
    _DEVARRAY_CELL_WARNED.add(key)
    warnings.warn(
        f"TRACED-CAPTURE: comqueue stage {fn_name!r}: closure variable "
        f"{cell_name!r} "
        f"captures a device array (jax.Array). The program cache "
        f"tokenizes device arrays by shape/dtype only, so if its CONTENT "
        f"changes between execs a stale compiled program would be reused "
        f"silently. Route data through init_with_partitioned_data/"
        f"init_with_broadcast_data instead, or set "
        f"ALINK_VERIFY_PROGRAM_CACHE=1 to catch drift by jaxpr "
        f"comparison.", RuntimeWarning, stacklevel=3)


def _callable_digest(fn, depth=4):
    """Structural token of a stage callable: bytecode + constants + frozen
    closure cells (+ bound-object public attrs for methods). Appended to
    the program-cache key so a caller whose ``program_key`` under-specifies
    a baked constant gets a cache MISS instead of a silently stale
    program (advisor r4, comqueue.py:57)."""
    import functools
    if isinstance(fn, functools.partial):
        return ("partial", _callable_digest(fn.func, depth),
                _freeze_closure_value(fn.args, depth),
                _freeze_closure_value(fn.keywords, depth))
    if hasattr(fn, "__wrapped__"):  # functools.wraps / jit-style wrappers
        return ("wrapped", _callable_digest(fn.__wrapped__, depth))
    if hasattr(fn, "__func__"):  # bound method: include the receiver's config
        self_tok = _freeze_closure_value(getattr(fn, "__self__", None), depth)
        return ("bound", _callable_digest(fn.__func__, depth), self_tok)
    code = getattr(fn, "__code__", None)
    if code is None:
        call = getattr(type(fn), "__call__", None)
        inner = getattr(call, "__code__", None)
        if inner is None:
            return ("opaque_callable", type(fn).__module__, type(fn).__qualname__)
        return ("callable_obj", _callable_digest(call.__get__(fn), depth))
    import hashlib
    h = hashlib.blake2b(code.co_code, digest_size=12)
    for c in code.co_consts:
        if isinstance(c, (bool, int, float, str, bytes, type(None))):
            h.update(repr(c).encode())
        elif hasattr(c, "co_code"):  # nested lambda/comprehension bodies
            h.update(c.co_code)
        else:
            h.update(type(c).__name__.encode())
    defaults = ()
    if fn.__defaults__ or getattr(fn, "__kwdefaults__", None):
        # default-arg values bake into the trace exactly like closure
        # cells do (the `def stage(ctx, scale=scale)` idiom); they must
        # ride in the digest or two structurally-different programs
        # would collide
        defaults = (_freeze_closure_value(fn.__defaults__, depth),
                    _freeze_closure_value(fn.__kwdefaults__, depth))
    cells = []
    if fn.__closure__:
        for name, cell in zip(code.co_freevars, fn.__closure__):
            try:
                v = cell.cell_contents
            except ValueError:
                # unbound cell (a closure var referenced before assignment,
                # e.g. a self-referential recursive fn being built): the
                # digest must be TOTAL, so degrade to an opaque token
                cells.append((name, ("opaque", "unbound_cell")))
                continue
            if _contains_devarray(v):
                _warn_devarray_cell(
                    code.co_name, name,
                    key=(getattr(fn, "__module__", ""),
                         getattr(fn, "__qualname__", code.co_name), name))
            cells.append((name, _freeze_closure_value(v, depth)))
    return (code.co_name, h.hexdigest(), tuple(cells), defaults)


# stage object -> digest. Digesting re-hashes every closure cell (data
# arrays included), so repeated exec() on the same queue object paid the
# full walk per cache HIT. Keyed on the stage OBJECT: a stage's closure
# contents are frozen at construction by the set_program_key contract
# (data flows through partitioned/broadcast inputs, never closures), so
# object identity implies digest identity.
_STAGE_DIGEST_MEMO: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _memo_digest(obj, compute):
    from ..common.metrics import env_flag
    if env_flag("ALINK_VERIFY_PROGRAM_CACHE", default=False):
        # debug mode bypasses the memo: a stage whose closure contents
        # mutated after its first exec (violating the identity contract
        # above) re-hashes fresh, so the jaxpr-compare guard downstream
        # sees the drifted key instead of a stale memo hiding it
        return compute()
    try:
        d = _STAGE_DIGEST_MEMO.get(obj)
    except TypeError:       # not weakref-able: compute every time
        return compute()
    if d is None:
        d = compute()
        try:
            _STAGE_DIGEST_MEMO[obj] = d
        except TypeError:
            pass
    return d


def _stages_digest(stages, criterion) -> tuple:
    items = []
    for s in stages:
        items.append(_memo_digest(s, lambda s=s: _callable_digest(
            s.fn if isinstance(s, _FnStage) else s.calc)))
    if criterion is not None:
        items.append(_memo_digest(criterion,
                                  lambda: _callable_digest(criterion)))
    return tuple(items)


def lazy_jit(fn, static_argnums=()):
    """Persistent jit wrapper for a module-level function. Calling
    ``jax.jit(fn)(...)`` inline creates a fresh wrapper — and a fresh
    trace — on every call; this memoizes the wrapper per (fn, statics)."""
    return _lazy_jit_cached(fn, tuple(static_argnums))


def _lazy_jit_cached(fn, static_argnums):
    key = (fn, static_argnums)
    got = _LAZY_JIT.get(key)
    if got is None:
        import jax
        got = _LAZY_JIT[key] = jax.jit(fn, static_argnums=static_argnums)
    return got


_LAZY_JIT: Dict[tuple, Callable] = {}


class ComputeFunction:
    """One per-worker compute stage (reference comqueue/ComputeFunction.java)."""

    def calc(self, context: ComContext):  # pragma: no cover - interface
        raise NotImplementedError


class _FnStage(ComputeFunction):
    def __init__(self, fn: Callable[[ComContext], None], name: str = ""):
        self.fn = fn
        self.__name__ = name or getattr(fn, "__name__", "stage")

    def calc(self, context: ComContext):
        self.fn(context)


def _readonly(arr: np.ndarray) -> np.ndarray:
    """Flip a host array read-only. Fetched results are MEMOIZED and
    shared between shards()/get()/concat() callers — a caller writing into
    one would silently corrupt every later read, so the memo only ever
    hands out non-writeable arrays (mutators get a loud ValueError and
    must copy)."""
    arr.flags.writeable = False
    return arr


def _fetch_tree(tree):
    """ONE batched device->host fetch of every leaf in ``tree`` (the
    shared ``common.compat.device_get_tree`` idiom), with every returned
    leaf flipped read-only (the memo contract above)."""
    import jax
    from ..common.compat import device_get_tree
    if not profile_enabled():
        return jax.tree_util.tree_map(_readonly, device_get_tree(tree))
    # measured-profiling D2H mark: result fetches are the transfer leg
    # of the workload attribution. The fetch itself is unchanged (same
    # one batched device_get; leaves stay read-only — memo contract).
    t0 = time.perf_counter()
    got = device_get_tree(tree)
    dt = time.perf_counter() - t0
    nbytes = sum(getattr(leaf, "nbytes", 0)
                 for leaf in jax.tree_util.tree_leaves(got))
    profile_mark("comqueue.fetch", "transfer", dt, nbytes=int(nbytes))
    return jax.tree_util.tree_map(_readonly, got)


class ComQueueResult:
    """Final per-worker state, stacked on a leading worker axis.

    Host arrays returned by ``shards()``/``get()`` are read-only views of
    a per-name memo; ``np.array(...)`` them to get a private writable
    copy."""

    def __init__(self, stacked: Dict[str, Any], num_workers: int,
                 totals: Dict[str, int]):
        self._stacked = stacked
        self.num_workers = num_workers
        self.totals = totals
        self._fetched: Dict[tuple, Any] = {}

    def shards(self, name: str):
        """(num_workers, ...) stacked per-worker values (read-only).

        Multi-leaf carry objects fetch in ONE batched ``jax.device_get``
        (see :func:`_fetch_tree`) — one link round trip per call, not
        per leaf."""
        if name not in self._stacked:
            raise KeyError(f"no carry object '{name}'; have {sorted(self._stacked)}")
        got = self._fetched.get(("shards", name))
        if got is None:
            got = self._fetched[("shards", name)] = _fetch_tree(
                self._stacked[name])
        return got

    def get(self, name: str):
        """Worker 0's copy (read-only) — use for replicated
        (post-allreduce) state.

        Slices BEFORE fetching (x[0] on device): fetching the full
        (num_workers, ...) stack and discarding all but shard 0 on host
        would pay num_workers x the bytes over the device link. Fetched
        leaves are memoized per name, so repeated get() calls pay the
        link once (advisor r4); multi-leaf objects fetch in ONE batched
        ``jax.device_get``."""
        import jax
        got = self._fetched.get(("get", name))
        if got is None:
            # memo first: after release() a get()-only name serves from
            # its memo even though the stacked entry is gone
            if name not in self._stacked:
                raise KeyError(f"no carry object '{name}'; "
                               f"have {sorted(self._stacked)}")
            full = self._fetched.get(("shards", name))
            if full is not None:  # already on host: slice locally
                got = jax.tree_util.tree_map(lambda x: x[0], full)
            else:
                got = _fetch_tree(jax.tree_util.tree_map(
                    lambda x: x[0], self._stacked[name]))
            self._fetched[("get", name)] = got
        return got

    def release(self, keep: Sequence[str] = ()) -> "ComQueueResult":
        """Detach to host and drop every device reference so the superstep
        carry (sk/yk ring buffers, per-row margins, ...) stops pinning
        HBM. Carries named in ``keep`` or previously read via ``shards()``
        stay fully readable; carries read only via ``get()`` keep serving
        ``get()`` from the memo (their per-worker stacks are gone); all
        other device state is discarded. Callers that retain results
        across many cached fits should call this once they are done
        reading device state (advisor r4)."""
        for name in keep:
            self.shards(name)
        # names never fetched are dropped; fetched ones now back _stacked
        # as host arrays, so shards()/get() keep working after release
        self._stacked = {k: self._fetched[("shards", k)]
                         for k in self._stacked
                         if ("shards", k) in self._fetched}
        return self

    def concat(self, name: str, total: Optional[int] = None):
        """Concatenate per-worker shards along axis 0 (departitioning).

        Zero-padding added by ``init_with_partitioned_data`` sits at the end
        of the global order, so per-row outputs aligned with a partitioned
        input can be trimmed with ``total`` (defaults to the input total when
        unambiguous).
        """
        v = self.shards(name)
        out = np.concatenate(list(v), axis=0)
        if total is None and len(set(self.totals.values())) == 1:
            total = next(iter(self.totals.values()), None)
        return out if total is None else out[:total]

    @property
    def step_count(self) -> int:
        return int(self.get("__step"))

    def keys(self):
        return [k for k in self._stacked.keys() if not k.startswith("__")]

    # -- health probe channel (common/health.py) -------------------------
    def probe_names(self):
        """Names published via ``ctx.probe`` during the run (sorted)."""
        pre = ComContext.PROBE_PREFIX
        return sorted(k[len(pre):] for k in self._stacked
                      if k.startswith(pre))

    def probe_series(self, name: str, trim: bool = True):
        """One probe's per-superstep series (worker 0's copy — probes
        conventionally record replicated post-allreduce scalars). With
        ``trim`` the NaN prefill past the executed step count is cut, so
        ``series[i]`` is superstep ``i + 1``'s value."""
        s = self.get(ComContext.PROBE_PREFIX + name)
        return s[:self.step_count] if trim else s

    def probes(self, trim: bool = True):
        """Every probe series as ``{name: (steps,) array}`` (read-only).

        All not-yet-memoized series (plus the ``__step`` count the trim
        needs) fetch in ONE batched ``jax.device_get`` — a run with a
        dozen probes pays one link round trip here, not thirteen."""
        import jax
        pre = ComContext.PROBE_PREFIX
        names = self.probe_names()
        missing = [pre + n for n in names
                   if ("get", pre + n) not in self._fetched]
        if trim and ("get", "__step") not in self._fetched \
                and "__step" in self._stacked:
            missing.append("__step")
        if missing:
            sliced = [jax.tree_util.tree_map(lambda x: x[0],
                                             self._stacked[k])
                      for k in missing]
            fetched = jax.device_get(sliced)
            for k, v in zip(missing, fetched):
                self._fetched[("get", k)] = jax.tree_util.tree_map(
                    lambda x: _readonly(np.asarray(x)), v)
        return {n: self.probe_series(n, trim=trim) for n in names}


class IterativeComQueue:
    def __init__(self, env: Optional[MLEnvironment] = None, max_iter: int = 100,
                 seed: int = 0, checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 1, checkpoint_keep: int = 3,
                 resume_from: Optional[str] = None):
        self.env = env
        self.max_iter = max_iter
        self.seed = seed
        self._stages: List[ComputeFunction] = []
        self._partitioned: Dict[str, np.ndarray] = {}
        self._broadcast: Dict[str, Any] = {}
        self._criterion: Optional[Callable[[ComContext], Any]] = None
        self._close: Optional[Callable[[ComQueueResult], Any]] = None
        self._program_key: Optional[tuple] = None
        self._ckpt = None
        self._boundary = None     # (every, hook) — set_boundary
        self._health = None       # HealthMonitor (set_health)
        self._data_token = None   # checkpoint-signature memo (see _run)
        if checkpoint_dir is not None:
            self.set_checkpoint(checkpoint_dir, every=checkpoint_every,
                                keep_last=checkpoint_keep,
                                resume_from=resume_from)
        elif resume_from is not None:
            raise ValueError("resume_from= requires checkpoint_dir= "
                             "(an explicit resume request must not "
                             "silently retrain from scratch)")

    # -- builder API (mirrors BaseComQueue.java:75-148) -------------------
    def init_with_partitioned_data(self, name: str, data) -> "IterativeComQueue":
        self._partitioned[name] = data
        self._data_token = None
        return self

    def init_with_broadcast_data(self, name: str, data) -> "IterativeComQueue":
        self._broadcast[name] = data
        self._data_token = None
        return self

    def add(self, stage) -> "IterativeComQueue":
        if callable(stage) and not isinstance(stage, (ComputeFunction, CommunicateFunction)):
            stage = _FnStage(stage)
        self._stages.append(stage)
        return self

    def set_compare_criterion(self, fn) -> "IterativeComQueue":
        """Stop when fn(context) is truthy; must read replicated state only."""
        self._criterion = fn
        return self

    # reference name (BaseComQueue.setCompareCriterionOfNode0)
    set_compare_criterion_of_node0 = set_compare_criterion

    def set_max_iter(self, n: int) -> "IterativeComQueue":
        self.max_iter = n
        return self

    def close_with(self, fn: Callable[[ComQueueResult], Any]) -> "IterativeComQueue":
        self._close = fn
        return self

    def set_program_key(self, key) -> "IterativeComQueue":
        """Opt into the compiled-program cache (see _PROGRAM_CACHE).

        ``key`` must be hashable and must determine the stage structure
        and every Python constant the stages close over; data must flow
        through partitioned/broadcast inputs only.
        """
        self._program_key = key
        return self

    def set_checkpoint(self, directory: str, every: int = 1,
                       keep_last: int = 3,
                       resume_from: Optional[str] = None
                       ) -> "IterativeComQueue":
        """Persist the superstep carry every ``every`` supersteps (and at
        the final state) under ``directory`` — durable, checksummed,
        atomically published snapshots (common/checkpoint.py), fetched
        to host OUTSIDE the compiled program. ``resume_from=`` restarts
        a killed run from its newest valid snapshot with bitwise-
        identical final results (engine/recovery.py)."""
        from .recovery import CheckpointConfig
        self._ckpt = CheckpointConfig(directory=str(directory),
                                      every=int(every),
                                      keep_last=int(keep_last),
                                      resume_from=resume_from)
        return self

    def set_boundary(self, every: int, hook) -> "IterativeComQueue":
        """Run the superstep loop CHUNKED with a host boundary hook every
        ``every`` supersteps: ``hook(stacked_carry, step) -> carry|None``
        may transform the carry between chunks (return ``None`` to keep
        it). The batched-carry entry point of the tuning sweep
        (``alink_tpu/tuning/``): ASHA rung decisions read the per-point
        probe lanes from the boundary carry and flip the carry-resident
        alive mask — the compiled chunk programs never change (the chunk
        limit is a traced scalar), so pruning can never recompile.

        Composes with :meth:`set_checkpoint`: when both are set the
        boundary cadence wins (the sweep aligns its rung period with the
        snapshot cadence) and the hook runs right after each snapshot
        publishes — and again after a resume, so a resumed run re-derives
        the same deterministic boundary decisions. Without a checkpoint
        directory the same chunked programs run with persistence off."""
        if int(every) < 1:
            raise ValueError(f"set_boundary(every=) must be >= 1, "
                             f"got {every}")
        self._boundary = (int(every), hook)
        return self

    def set_health(self, monitor) -> "IterativeComQueue":
        """Attach a ``common.health.HealthMonitor``: after the run (and,
        for checkpointed runs, at every snapshot boundary — where the
        carry is already host-synced) the engine feeds it every
        ``ctx.probe`` series and calls ``evaluate()``. A monitor with
        ``raise_on={"critical"}`` therefore aborts a poisoned
        checkpointed run at the next boundary instead of burning the
        remaining superstep budget. No-op when ``ALINK_TPU_HEALTH`` is
        off (stages record no probes)."""
        self._health = monitor
        return self

    # -- execution --------------------------------------------------------
    def lowered(self):
        """Lower (but do not run) the whole-superstep SPMD program;
        returns the jax.stages.Lowered for HLO inspection — the scaling
        evidence tool reads the compiled collectives and their payload
        shapes from it (tools/scaling_evidence.py)."""
        return self._run(lower_only=True)

    def lowered_chunked(self):
        """Lower the CHECKPOINT-mode chunk programs; returns
        ``(first, cont)`` jax.stages.Lowered. The durability test asserts
        these carry no host callbacks and exactly the collectives of the
        unchunked program — checkpointing adds zero compiled ops."""
        return self._run(lower_only=True, lower_chunked=True)

    def exec(self):
        # one root span per exec: every phase span (prepare / execute via
        # StepTimer), chunk span and instant event below nests under it,
        # so a trace file reads as one tree per fit
        with trace_span("comqueue.exec", cat="engine") as sp:
            sp.set(max_iter=int(self.max_iter),
                   program=_program_label(self._program_key)
                   if self._program_key is not None else "uncached")
            return self._run(lower_only=False)

    def _run(self, lower_only: bool = False, lower_chunked: bool = False):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ..common.compat import shard_map

        from ..common.metrics import get_registry, metrics_enabled

        env = self.env or MLEnvironmentFactory.get_default()
        nw = env.num_workers
        mesh = env.mesh
        stages = list(self._stages)
        criterion = self._criterion
        max_iter = int(self.max_iter)
        seed = int(self.seed)
        mx = metrics_enabled() and not lower_only
        # key-folding flag dims, latched ONCE per run at the plan
        # derivation site (common/plan.engine_flags — the ENV-KEY-FOLD
        # checked site).  probes: stacked (max_iter,) carry entries make
        # a toggled flag a structurally different program.  donate: the
        # buffer-aliasing contract differs even though the HLO ops are
        # identical.  fuse: the fused program's collective set is
        # structurally different HLO.  All three (plus step_log) ride
        # the program-cache key via the ExecutionPlan below.
        from ..common import aotcache, compileledger
        from ..common import plan as planlib
        plan_flags = planlib.engine_flags()
        probes_on = plan_flags[1][1]
        donate = plan_flags[2][1]
        fuse = plan_flags[3][1]
        from .communication import fusing, resolve_deferred
        # per-superstep collective capture (trace-time; see communication
        # .collecting), keyed by the traced input signature: jax.jit keeps
        # a shape-keyed trace cache underneath each compiled entry, so one
        # cached program can hold several traces with different payload
        # sizes — each signature gets its own init/body manifest. A dict
        # so the superstep closure — which may be retraced later through a
        # CACHED program — always writes into the manifest object stored
        # with that program.
        manifest: Dict[tuple, Dict[str, list]] = {}

        parts: Dict[str, Any] = {}
        totals: Dict[str, int] = {}
        # measured-profiling transfer mark (ALINK_TPU_PROFILE): the
        # prepare phase is host padding + the H2D input ship — charged
        # to the transfer bucket of the workload attribution. Host-side
        # wall clock only; the compiled program is untouched.
        _prep_t0 = time.perf_counter()
        with _ENGINE_TIMER.span("comqueue.prepare"):
            for k, arr in self._partitioned.items():
                if isinstance(arr, jax.Array):
                    # already device-resident (e.g. precomputed one-hot design
                    # factors): pad on device — np.asarray would round-trip
                    # GBs through the host
                    totals[k] = int(arr.shape[0])
                    pad = (-arr.shape[0]) % nw
                    if pad:
                        arr = jnp.concatenate(
                            [arr, jnp.zeros((pad, *arr.shape[1:]), arr.dtype)],
                            axis=0)
                    parts[k] = arr
                    continue
                arr = np.asarray(arr)
                totals[k] = int(arr.shape[0])
                pad = (-arr.shape[0]) % nw
                if pad:
                    arr = np.concatenate(
                        [arr, np.zeros((pad, *arr.shape[1:]), dtype=arr.dtype)],
                        axis=0)
                parts[k] = jnp.asarray(arr)
            bcast = {k: jax.tree_util.tree_map(jnp.asarray, v)
                     for k, v in self._broadcast.items()}
            for k, n in totals.items():
                bcast[f"__total_{k}"] = jnp.asarray(n, jnp.int32)
        if not lower_only:
            profile_mark("comqueue.prepare", "transfer",
                         time.perf_counter() - _prep_t0)

        from ..common.profiling import log_superstep, named_stage
        from .communication import collecting

        def static_sig(static):
            """Trace signature: per-worker shapes/dtypes of every input
            leaf, computed identically on host inputs (given the P('d')
            leading-axis split) and on the tracers inside superstep."""
            items = []
            for k in sorted(static):
                for leaf in jax.tree_util.tree_leaves(static[k]):
                    items.append((k, tuple(map(int, leaf.shape)),
                                  str(leaf.dtype)))
            return tuple(items)

        def superstep(carry, static, init_pass):
            ctx = ComContext(carry, static, nw, init_pass,
                             max_iter=max_iter, probes_on=probes_on)
            # capture this pass's collectives at TRACE time (shapes are on
            # the tracers; nothing is added to the compiled program).
            # clear() first: a retrace through a cached program must
            # OVERWRITE the stored per-pass manifest, not append to it.
            per = manifest.setdefault(static_sig(static),
                                      {"init": [], "body": []})
            entries = per["init" if init_pass else "body"]
            entries.clear()
            with collecting(entries):
                # fusion scope (no-op when the flag is off): manifest
                # wrappers defer their reductions; the first USE of any
                # deferred value flushes all independent pending payloads
                # as one flattened collective, and the scope exit flushes
                # whatever was never read inside this superstep
                with fusing(enabled=fuse):
                    for s in stages:
                        # name each compiled stage (the reference .name()s
                        # every dataflow stage for the Flink UI,
                        # BaseComQueue.java:172-195)
                        with named_stage(getattr(s, "__name__",
                                                 type(s).__name__)):
                            s.calc(ctx)
                    if criterion is not None:
                        stop = criterion(ctx)
                        ctx.put_obj("__stop",
                                    jnp.asarray(stop, bool).reshape(()))
                    else:
                        ctx.put_obj("__stop", jnp.asarray(False))
            if fuse:
                # deferred proxies must never reach the while_loop carry
                for k in list(ctx.carry):
                    ctx.carry[k] = resolve_deferred(ctx.carry[k])
            log_superstep(ctx.step_no, task=ctx.task_id,
                          stop=ctx.get_obj("__stop"))
            return ctx.carry

        def run(parts_shard, bcast_rep):
            static = {**parts_shard, **bcast_rep}
            carry = {"__step": jnp.asarray(1, jnp.int32),
                     "__key": jax.random.PRNGKey(seed)}
            carry = superstep(carry, static, init_pass=True)

            def body(c):
                c = dict(c)
                c["__step"] = c["__step"] + 1
                return superstep(c, static, init_pass=False)

            def cond(c):
                return (c["__step"] < max_iter) & jnp.logical_not(c["__stop"])

            final = jax.lax.while_loop(cond, body, carry) if max_iter > 1 else carry
            # uniform out_spec: every leaf gains a leading worker axis
            return jax.tree_util.tree_map(lambda x: jnp.expand_dims(x, 0), final)

        def build_mapped():
            # ONE construction shared by lowered() and exec(): the HLO
            # audit must inspect exactly the program exec runs
            return shard_map(run, mesh=mesh, in_specs=(P("d"), P()),
                             out_specs=P("d"), check_vma=False)

        # -- checkpoint-mode chunk programs -------------------------------
        # The SAME superstep body, but the loop's upper bound is a TRACED
        # scalar: one compiled program serves every chunk between
        # checkpoint boundaries, and the host persists the carry between
        # chunk calls (engine/recovery.py). ``first`` runs the init pass;
        # ``cont`` re-enters with a (possibly disk-round-tripped) stacked
        # carry.
        def chunk_body_cond(static, limit):
            def body(c):
                c = dict(c)
                c["__step"] = c["__step"] + 1
                return superstep(c, static, init_pass=False)

            def cond(c):
                return ((c["__step"] < limit) & (c["__step"] < max_iter)
                        & jnp.logical_not(c["__stop"]))
            return body, cond

        def build_first_chunk():
            def run_first(parts_shard, bcast_rep, limit):
                static = {**parts_shard, **bcast_rep}
                carry = {"__step": jnp.asarray(1, jnp.int32),
                         "__key": jax.random.PRNGKey(seed)}
                carry = superstep(carry, static, init_pass=True)
                body, cond = chunk_body_cond(static, limit)
                final = jax.lax.while_loop(cond, body, carry) \
                    if max_iter > 1 else carry
                return jax.tree_util.tree_map(
                    lambda x: jnp.expand_dims(x, 0), final)
            return shard_map(run_first, mesh=mesh,
                             in_specs=(P("d"), P(), P()),
                             out_specs=P("d"), check_vma=False)

        def build_cont_chunk():
            def run_cont(parts_shard, bcast_rep, carry_stacked, limit):
                static = {**parts_shard, **bcast_rep}
                carry = jax.tree_util.tree_map(
                    lambda x: jnp.squeeze(x, 0), dict(carry_stacked))
                body, cond = chunk_body_cond(static, limit)
                final = jax.lax.while_loop(cond, body, carry)
                return jax.tree_util.tree_map(
                    lambda x: jnp.expand_dims(x, 0), final)
            return shard_map(run_cont, mesh=mesh,
                             in_specs=(P("d"), P(), P("d"), P()),
                             out_specs=P("d"), check_vma=False)

        def jit_cont():
            # carry donation (ALINK_TPU_DONATE): argnum 2 is the stacked
            # chunk carry — the ONLY input a chunk pass consumes. parts/
            # bcast are never donatable (every later chunk re-reads them)
            return jax.jit(build_cont_chunk(),
                           donate_argnums=(2,) if donate else ())

        if lower_only:
            if not lower_chunked:
                return jax.jit(build_mapped()).lower(parts, bcast)
            lim = jnp.asarray(max_iter, jnp.int32)
            first_fn = jax.jit(build_first_chunk())
            first_low = first_fn.lower(parts, bcast, lim)
            # the cont program's carry geometry comes from the first
            # program's abstract output — no execution, no compile
            carry_shape = jax.eval_shape(first_fn, parts, bcast, lim)
            cont_low = jit_cont().lower(parts, bcast, carry_shape, lim)
            return first_low, cont_low
        compiled = None
        ckey = None
        cache_status = "uncached"
        stages_dig = None
        if self._program_key is not None or self._ckpt is not None:
            stages_dig = _stages_digest(stages, criterion)
        # ONE ExecutionPlan per exec (ROADMAP item 1): the program-cache
        # key and the recovery signature both derive from it.  The
        # structural guard stays (advisor r4): the stage bytecode +
        # frozen closure cells ride in the "stages" dim, so a
        # program_key that under-specifies a baked constant misses
        # instead of silently re-running a stale program.
        splan = planlib.engine_plan(
            program_key=self._program_key, stages_digest=stages_dig,
            mesh=mesh, num_workers=nw, max_iter=max_iter, seed=seed,
            has_criterion=criterion is not None, flags=plan_flags,
            part_names=tuple(sorted(parts)),
            bcast_names=tuple(sorted(bcast)))
        if self._program_key is not None:
            ckey = splan.legacy_key()
        if not lower_only:
            compileledger.subsystem_start("engine")

        if self._ckpt is not None or self._boundary is not None:
            # -- durable chunked execution (engine/recovery.py) -----------
            from . import recovery
            if jax.process_count() > 1:
                raise NotImplementedError(
                    "comqueue checkpointing is single-process for now: the "
                    "per-boundary carry fetch would need a multihost "
                    "allgather + single-writer election")
            ck = self._ckpt
            on_boundary = None
            if self._boundary is not None:
                # boundary-driven chunking (tuning sweep rungs): the hook
                # cadence overrides the snapshot cadence — the sweep
                # aligns both, and a hook without set_checkpoint runs the
                # chunk programs with persistence off (directory=None)
                b_every, on_boundary = self._boundary
                if ck is None:
                    ck = recovery.CheckpointConfig(directory=None,
                                                   every=b_every)
                elif int(ck.every) != b_every:
                    import dataclasses
                    ck = dataclasses.replace(ck, every=b_every)
            first = cont = None
            ckkey = ("__ckpt__", ckey) if ckey is not None else None
            aot_first_plan = aot_cont_plan = None
            if ckkey is not None:
                compileledger.register_cache("engine.chunked", "engine",
                                             _PROGRAM_CACHE_MAX)
                cached = _PROGRAM_CACHE.get(ckkey)
                if cached is not None:
                    cache_status = "hit"
                    _PROGRAM_CACHE_STATS["hits"] += 1
                    _PROGRAM_CACHE.move_to_end(ckkey)
                    first, cont = cached
                    manifest = _PROGRAM_CACHE_MANIFESTS.setdefault(ckkey,
                                                                   manifest)
                    compileledger.record_hit("engine.chunked")
            if (first is None and ckkey is not None and aotcache.active()
                    and jax.process_count() == 1):
                # load-before-compile (ISSUE 20): the chunked pair ships
                # as two artifacts keyed off the same plan with a role
                # dim.  Both must load or neither installs (a half pair
                # would force a recompile anyway), so record=False here
                # and the ledger disk-hit is written only on full success
                _base = splan.extend(("checkpoint_chunked", True))
                aot_first_plan = _base.extend(("role", "first"))
                aot_cont_plan = _base.extend(("role", "cont"))
                _site = _program_label(self._program_key)
                lf = aotcache.load(aot_first_plan, cache="engine.chunked",
                                   site=_site, subsystem="engine",
                                   record=False)
                lc = aotcache.load(aot_cont_plan, cache="engine.chunked",
                                   site=_site, subsystem="engine",
                                   record=False) if lf is not None else None
                if lf is not None and lc is not None:
                    first = _AotMeshCall(lf.fn, mesh,
                                         ("shard", "repl", "repl"))
                    cont = _AotMeshCall(lc.fn, mesh,
                                        ("shard", "repl", "shard", "repl"))
                    cache_status = "disk-hit"
                    _PROGRAM_CACHE_STATS["hits"] += 1
                    _PROGRAM_CACHE[ckkey] = (first, cont)
                    # the deserialized programs never trace, so the
                    # per-superstep collective manifest rides the artifact
                    # header instead of the closure
                    _m = lf.manifest(None)
                    if isinstance(_m, dict) and _m:
                        manifest.update(_m)
                    _PROGRAM_CACHE_MANIFESTS[ckkey] = manifest
                    for _lp in (lf, lc):
                        compileledger.record_disk_hit(
                            "engine.chunked", _base, wall_s=_lp.wall_s,
                            site=_site, subsystem="engine")
            if first is None:
                first = jax.jit(build_first_chunk())
                cont = jit_cont()
                if ckkey is not None:
                    cache_status = "miss"
                    _PROGRAM_CACHE_STATS["misses"] += 1
                    _PROGRAM_CACHE[ckkey] = (first, cont)
                    _PROGRAM_CACHE_MANIFESTS[ckkey] = manifest
                    compileledger.record_event(
                        "engine.chunked",
                        splan.extend(("checkpoint_chunked", True)),
                        site=_program_label(self._program_key),
                        subsystem="engine")
                    while len(_PROGRAM_CACHE) > _PROGRAM_CACHE_MAX:
                        old_key, _ = _PROGRAM_CACHE.popitem(last=False)
                        _PROGRAM_CACHE_JAXPRS.pop(old_key, None)
                        _PROGRAM_CACHE_MANIFESTS.pop(old_key, None)
                        _PROGRAM_CACHE_COSTS.pop(old_key, None)
                        compileledger.record_eviction(
                            "engine.chunked"
                            if old_key and old_key[0] == "__ckpt__"
                            else "engine.program")
                    if aot_first_plan is not None:
                        # export BEFORE recovery.drive: export's trace runs
                        # the superstep closures, so the collective
                        # manifest is populated by the time the header
                        # snapshots it.  Gate the cont store on the first:
                        # a half pair on disk would never install
                        _site = _program_label(self._program_key)
                        _lim0 = jnp.asarray(int(max_iter), jnp.int32)
                        if aotcache.store(aot_first_plan, first,
                                          (parts, bcast, _lim0),
                                          cache="engine.chunked",
                                          site=_site, manifest=manifest):
                            _carry_av = jax.eval_shape(first, parts, bcast,
                                                       _lim0)
                            aotcache.store(aot_cont_plan, cont,
                                           (parts, bcast, _carry_av, _lim0),
                                           cache="engine.chunked",
                                           site=_site, manifest=manifest)
            if mx and ckkey is not None:
                get_registry().inc("alink_comqueue_program_cache_total", 1,
                                   {"result": cache_status})
            if ckkey is not None:
                trace_instant("comqueue.program_cache", cat="engine",
                              args={"result": cache_status})
            cost = _maybe_cost(ckkey, lambda: first.lower(
                parts, bcast, jnp.asarray(max_iter, jnp.int32)))
            if ck.directory or ck.resume_from:
                part_sig = tuple(
                    (k, tuple(map(int, np.shape(parts[k]))),
                     str(getattr(parts[k], "dtype", "?")))
                    for k in sorted(parts))
                # fingerprint the ORIGINAL (pre-padding, host-side)
                # inputs: np arrays hash by content, device-resident
                # arrays degrade to shape/dtype tokens (no forced
                # device->host round trip). Memoized per queue instance
                # (invalidated by init_with_*): repeated exec() on the
                # same queue must not re-hash the whole dataset per
                # program-cache hit
                data_token = self._data_token
                if data_token is None:
                    data_token = self._data_token = _freeze_closure_value(
                        {"parts": dict(self._partitioned),
                         "bcast": dict(self._broadcast)}, 3)
                # the durable-run signature derives from the SAME plan
                # as the program-cache key (content identical to the
                # historical direct program_signature call — old
                # snapshots stay resumable)
                signature = planlib.engine_checkpoint_signature(
                    splan, part_sig=part_sig, data_token=data_token)
                resumed = recovery.resume_state(ck, signature)
            else:
                # boundary-only chunking (set_boundary without a
                # checkpoint dir): nothing persists and nothing resumes,
                # so content-hashing the whole dataset for a signature
                # no snapshot will ever carry is pure waste
                signature, resumed = None, None
            on_snapshot = None
            if self._health is not None and probes_on:
                # mid-run watchdog: evaluate on the carry the boundary
                # save just fetched — zero extra device->host traffic.
                # evaluate() may raise HealthAlertError (raise_on=...),
                # aborting AFTER the snapshot published, so the run stays
                # resumable/inspectable
                def on_snapshot(host, step, _m=self._health):
                    self._ingest_probes(_m, host, step)
            with _ENGINE_TIMER.span("comqueue.execute",
                                    labels={"program": cache_status}):
                stacked, ck_info = recovery.drive(
                    ck, first=first, cont=cont, parts=parts, bcast=bcast,
                    max_iter=max_iter, signature=signature, resumed=resumed,
                    on_snapshot=on_snapshot, donate=donate,
                    on_boundary=on_boundary)
            # chunked path: the program runs once per chunk, so only the
            # STATIC cost gauges are meaningful (no exec_t0 -> no achieved
            # rates; see _finish)
            return self._finish(stacked, nw, totals, manifest, parts, bcast,
                                mx, ck_info, cost=cost,
                                prog_label=_program_label(self._program_key)
                                if self._program_key is not None else None,
                                probes_on=probes_on)
        from ..common.metrics import env_flag
        verify = env_flag("ALINK_VERIFY_PROGRAM_CACHE", default=False)
        if ckey is not None:
            compileledger.register_cache("engine.program", "engine",
                                         _PROGRAM_CACHE_MAX)
            compiled = _PROGRAM_CACHE.get(ckey)
        # verify mode is excluded: it compares fresh jaxprs against the
        # trace recorded at compile time, and a deserialized program has
        # no trace to baseline against
        aot_plain = (ckey is not None and not verify
                     and jax.process_count() == 1 and aotcache.active())
        disk_hit = False
        if compiled is None and aot_plain:
            loaded = aotcache.load(splan, cache="engine.program",
                                   site=_program_label(self._program_key),
                                   subsystem="engine")
            if loaded is not None:
                compiled = _AotMeshCall(loaded.fn, mesh, ("shard", "repl"))
                disk_hit = True
                cache_status = "disk-hit"
                _PROGRAM_CACHE_STATS["hits"] += 1
                _PROGRAM_CACHE[ckey] = compiled
                # deserialized programs never trace, so the collective
                # manifest comes from the artifact header
                _m = loaded.manifest(None)
                if isinstance(_m, dict) and _m:
                    manifest.update(_m)
                _PROGRAM_CACHE_MANIFESTS[ckey] = manifest
                while len(_PROGRAM_CACHE) > _PROGRAM_CACHE_MAX:
                    old_key, _ = _PROGRAM_CACHE.popitem(last=False)
                    _PROGRAM_CACHE_JAXPRS.pop(old_key, None)
                    _PROGRAM_CACHE_MANIFESTS.pop(old_key, None)
                    _PROGRAM_CACHE_COSTS.pop(old_key, None)
                    compileledger.record_eviction(
                        "engine.chunked"
                        if old_key and old_key[0] == "__ckpt__"
                        else "engine.program")
        if compiled is None:
            compiled = jax.jit(build_mapped())
            if ckey is not None:
                cache_status = "miss"
                _PROGRAM_CACHE_STATS["misses"] += 1
                _PROGRAM_CACHE[ckey] = compiled
                # ledger event at insert time; the trace+compile wall is
                # only observable around the first dispatch (jit is
                # lazy) — note_wall below attaches it
                compileledger.record_event(
                    "engine.program", splan,
                    site=_program_label(self._program_key),
                    subsystem="engine")
                # the cached program's superstep closure writes into THIS
                # manifest dict; store it so later cache-hit execs can
                # read the per-superstep collective capture
                _PROGRAM_CACHE_MANIFESTS[ckey] = manifest
                if verify:
                    # baseline jaxpr recorded AT COMPILE TIME, so the very
                    # first post-compile drift is caught on the next hit
                    _PROGRAM_CACHE_JAXPRS[ckey] = str(
                        jax.make_jaxpr(build_mapped())(parts, bcast))
                while len(_PROGRAM_CACHE) > _PROGRAM_CACHE_MAX:
                    old_key, _ = _PROGRAM_CACHE.popitem(last=False)
                    _PROGRAM_CACHE_JAXPRS.pop(old_key, None)
                    _PROGRAM_CACHE_MANIFESTS.pop(old_key, None)
                    _PROGRAM_CACHE_COSTS.pop(old_key, None)
                    compileledger.record_eviction(
                        "engine.chunked"
                        if old_key and old_key[0] == "__ckpt__"
                        else "engine.program")
        elif ckey is not None and not disk_hit:
            cache_status = "hit"
            _PROGRAM_CACHE_STATS["hits"] += 1
            _PROGRAM_CACHE.move_to_end(ckey)
            compileledger.record_hit("engine.program")
            # the cached closure traces into the manifest stored at miss
            # time, not this exec's local dict — read from the stored one
            manifest = _PROGRAM_CACHE_MANIFESTS.setdefault(ckey, manifest)
            if verify:
                # debug mode: re-trace on every hit and compare jaxprs —
                # catches any constant the structural guard cannot see
                fresh = str(jax.make_jaxpr(build_mapped())(parts, bcast))
                seen = _PROGRAM_CACHE_JAXPRS.setdefault(ckey, fresh)
                if fresh != seen:
                    raise RuntimeError(
                        "ALINK_VERIFY_PROGRAM_CACHE: cached program for key "
                        f"{self._program_key!r} no longer matches a fresh "
                        "trace — a stage closure baked state the program_key "
                        "does not cover")
        if mx and ckey is not None:
            get_registry().inc("alink_comqueue_program_cache_total", 1,
                               {"result": cache_status})
        if ckey is not None:
            trace_instant("comqueue.program_cache", cat="engine",
                          args={"result": cache_status})
        cost = _maybe_cost(ckey, lambda: compiled.lower(parts, bcast))
        exec_t0 = time.perf_counter()
        with _ENGINE_TIMER.span("comqueue.execute",
                                labels={"program": cache_status}):
            # measured-profiling window (ALINK_TPU_PROFILE): dispatch =
            # time the compiled call held the host thread (includes
            # trace+compile on a cache miss — the label says which);
            # device = time an explicit block_until_ready waited on the
            # program. The extra sync only exists under the flag and
            # changes timing, never values or compiled HLO.
            with profile_window("comqueue.exec", label=cache_status,
                                capture=True) as pw:
                _pt0 = time.perf_counter()
                stacked = compiled(parts, bcast)
                _disp = time.perf_counter() - _pt0
                pw.dispatch(_disp)
                if cache_status == "miss":
                    # the first dispatch carried trace+compile — attach
                    # its wall to this miss's ledger entry
                    compileledger.note_wall("engine.program", _disp)
                if pw.on:
                    _pt1 = time.perf_counter()
                    jax.block_until_ready(stacked)
                    pw.device(time.perf_counter() - _pt1)
        hbm_snapshot("comqueue.exec")
        if cache_status == "miss" and aot_plain:
            # persist off the hot path, after the first dispatch: the
            # export re-trace refreshes the same manifest dict the miss
            # installed (superstep capture is overwrite-safe)
            aotcache.store(splan, compiled, (parts, bcast),
                           cache="engine.program",
                           site=_program_label(self._program_key),
                           manifest=_PROGRAM_CACHE_MANIFESTS.get(
                               ckey, manifest))
        if jax.process_count() > 1:
            # multi-host session: leaves span non-addressable devices —
            # gather every worker's shard to every host before fetching
            # (the reference's result collection back to the client)
            from jax.experimental import multihost_utils
            stacked = jax.tree_util.tree_map(
                lambda x: np.asarray(
                    multihost_utils.process_allgather(x, tiled=True)),
                stacked)
        return self._finish(stacked, nw, totals, manifest, parts, bcast,
                            mx, None, cost=cost, exec_t0=exec_t0,
                            prog_label=_program_label(self._program_key)
                            if self._program_key is not None else None,
                            probes_on=probes_on)

    @staticmethod
    def _ingest_probes(monitor, host, step):
        """Feed the probe prefix of a host (stacked) carry to a
        HealthMonitor and evaluate. Worker 0's copy: probes record
        replicated post-allreduce scalars by convention."""
        pre = ComContext.PROBE_PREFIX
        series = {k[len(pre):]: np.asarray(v)[0][:int(step)]
                  for k, v in host.items() if k.startswith(pre)}
        if series:
            monitor.ingest(series)
            monitor.evaluate()

    def _finish(self, stacked, nw, totals, manifest, parts, bcast, mx,
                ck_info, cost=None, exec_t0=None, prog_label=None,
                probes_on=False):
        """Shared result assembly + metrics tail for the single-program
        and checkpoint-chunked execution paths. ``ck_info`` is the
        recovery driver's accounting (None on the single-program path).
        ``cost`` is the program's static XLA cost dict (tracing-only, see
        _maybe_cost); ``exec_t0`` the dispatch start on the single-program
        path, used for achieved-rate gauges."""
        import jax

        from ..common.metrics import get_registry

        # single-process: leave leaves ON DEVICE — ComQueueResult fetches
        # per access, so a fit that only reads coef + loss_curve does not
        # pull the whole carry (L-BFGS sk/yk ring buffers, per-row
        # margins, ...) through a slow host<->device link
        result = ComQueueResult(stacked, nw, totals)
        if mx:
            reg = get_registry()
            # one scalar fetch; on deferred backends this flushes the run,
            # which the caller's first result read would have done anyway
            steps = int(result.step_count)
            # a resumed run only EXECUTED the supersteps past its snapshot
            # (and no init pass); charge collectives/supersteps for those
            if ck_info is None:
                executed, init_runs = steps, 1
            else:
                init_runs = 1 if ck_info["init_ran"] else 0
                executed = ck_info["steps_executed"]
            reg.inc("alink_comqueue_execs_total", 1)
            reg.inc("alink_comqueue_supersteps_total", executed)
            # this exec's trace signature, computed on the HOST inputs
            # exactly as static_sig sees them inside shard_map: parts are
            # split on the leading axis by the worker count, bcast is
            # replicated unchanged
            items = []
            for k in sorted(set(parts) | set(bcast)):
                split = nw if k in parts else 1
                for leaf in jax.tree_util.tree_leaves(
                        parts[k] if k in parts else bcast[k]):
                    sh = tuple(map(int, leaf.shape))
                    if split > 1 and sh:
                        sh = (sh[0] // split,) + sh[1:]
                    items.append((k, sh, str(leaf.dtype)))
            per = manifest.get(tuple(items))
            if per is None and len(manifest) == 1:
                # defensive: a host/trace signature drift should not drop
                # attribution when only one trace exists
                per = next(iter(manifest.values()))
            # the init pass executed at most once (superstep 1; not at all
            # on a resumed run); the while-loop body executed the other
            # supersteps (the body is TRACED even for runs whose criterion
            # stops at step 1, so it must not be charged for supersteps it
            # never ran)
            # charge the captured manifests through the ONE fused-aware
            # replay helper (records are 3-tuples, or 4-tuples carrying
            # fused-group membership — communication.record_manifest)
            if per is not None:
                from .communication import record_manifest
                if init_runs > 0:
                    record_manifest(per["init"], times=init_runs)
                if executed - init_runs > 0:
                    record_manifest(per["body"],
                                    times=executed - init_runs)
            if cost is not None:
                # XLA's static cost model for this program (ALINK_TPU_TRACE
                # runs only — _maybe_cost). The step_count fetch above
                # flushed the run, so elapsed-since-dispatch is an honest
                # wall-clock bound for the achieved rates; NOTE the static
                # model costs a while-loop body ONCE, so treat achieved
                # figures as per-program-pass, not per-superstep totals.
                plbl = {"program": prog_label or "?"}
                flops = cost.get("flops")
                acc_bytes = cost.get("bytes accessed")
                if flops is not None:
                    reg.set_gauge("alink_program_flops", flops, plbl)
                if acc_bytes is not None:
                    reg.set_gauge("alink_program_bytes_accessed",
                                  acc_bytes, plbl)
                if exec_t0 is not None:
                    elapsed = time.perf_counter() - exec_t0
                    if elapsed > 0:
                        if flops:
                            reg.set_gauge("alink_program_achieved_flops_per_s",
                                          flops / elapsed, plbl)
                        if acc_bytes:
                            reg.set_gauge("alink_program_achieved_bytes_per_s",
                                          acc_bytes / elapsed, plbl)
        if self._health is not None and probes_on:
            # final pass (also re-runs after a chunked run's last
            # boundary ingest — alerts are deduped by the monitor). The
            # probe fetch is a handful of (max_iter,) f32 series
            names = result.probe_names()
            if names:
                self._health.ingest_result(result)
                self._health.evaluate()
        if self._close is not None:
            return self._close(result)
        return result
