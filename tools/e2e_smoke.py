#!/usr/bin/env python
"""Whole-loop online-DAG chaos smoke (perf_gate leg, ISSUE 15) — exit 9.

Runs the supervised online-learning DAG (alink_tpu/online/: ingest ->
FTRL -> hot-swap serving -> windowed eval, ONE program with per-stage
restart policy and an end-to-end SloContract) through scripted
``ALINK_TPU_FAULT_INJECT`` storms covering EVERY fault site at once,
and gates the whole-loop SLO contract:

  scenario 1 — deterministic-recovery storm (ftrl.batch kill mid-train
    + ckpt.save fault + prefetch.get delay): the supervisor restarts
    the trainer from its last checkpoint twice, and the run's eval
    windows, per-batch served scores AND final model are **bitwise
    identical** to the clean run's — the trainer resumed bitwise, no
    micro-batch was dropped or double-applied, and injected channel
    latency changed nothing but wall time.
  scenario 2 — degraded serving storm (serve.dispatch error storm +
    one corrupt model snapshot): the breaker opens and traffic
    degrades to the host fallback (correct answers — last-ulp detail
    drift is the documented compiled-vs-host posture, so the gate here
    is value-tolerance + a BITWISE tail once the breaker re-closes:
    measured recovery to the compiled path), the poisoned snapshot is
    skipped exactly once with the last good model still serving, and
    the armed SloContract's typed verdicts MATCH the storm (live p99
    breaches recorded; staleness and AUC clauses stay ok).
  scenario 3 — latency + deadline leg: an injected-slow dispatch plus
    tight-deadline side traffic sheds typed DeadlineExceeded, never
    silence.

Every scenario runs inside ``scoped_fault_env`` (counters reset on
entry, env restored + counters reset on exit, INCLUDING failure paths)
so no storm can bleed visit counters into the next. Zero silent drops
is asserted in every scenario: results + typed rejections ==
submissions, future by future.

Runs in a fresh child interpreter (bootenv CPU mesh) so fault counters
and the metrics registry start from zero.
"""

import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

EXIT = 9
_MARK = "ALINK_E2E_SMOKE_CHILD"

# scenario 1: trainer kill at batch 7 (the harness clears the entry at
# the supervisor's crash callback — the kill is keyed on the batch
# NUMBER, which a checkpoint replay revisits), 2nd checkpoint save
# faults transiently (auto-indexed: clears itself), every channel get
# runs 2 ms slow
STORM_DETERMINISTIC = ("ftrl.batch:7-7;ckpt.save:2-2:error;"
                       "prefetch.get:1-60:delay:2")
# scenario 2: 10-dispatch transient error window (trips the breaker)
# + the FIRST model snapshot emitted corrupt (the supervised feeder
# must skip it and keep the last good model)
STORM_DEGRADED = "serve.dispatch:1-10:error;feeder.snapshot:1-1:corrupt"
# scenario 3: one 30 ms slow dispatch for the deadline-shed leg
STORM_DELAY = "serve.dispatch:1:delay:30"


def main() -> int:
    if os.environ.get(_MARK) != "1":
        import bootenv
        env = bootenv.cpu_mesh_env(4)
        env[_MARK] = "1"
        env["JAX_ENABLE_X64"] = "1"
        env.pop("ALINK_TPU_FAULT_INJECT", None)
        env["ALINK_TPU_SERVE_BREAKER_MAX_MS"] = "200"
        out = subprocess.run([sys.executable, os.path.abspath(__file__)],
                             cwd=ROOT, env=env, timeout=900)
        return out.returncode

    import json
    import tempfile
    import warnings

    import numpy as np

    from alink_tpu.common.faults import FAULT_ENV, scoped_fault_env
    from alink_tpu.common.metrics import MetricsRegistry, set_registry
    from alink_tpu.common.mtable import MTable
    from alink_tpu.common.vector import DenseVector
    from alink_tpu.online import OnlineDag, SloContract
    from alink_tpu.operator.batch.classification.linear import (
        LogisticRegressionTrainBatchOp)
    from alink_tpu.operator.batch.source.sources import MemSourceBatchOp
    from alink_tpu.operator.stream.source.sources import MemSourceStreamOp

    warnings.filterwarnings("ignore", category=RuntimeWarning)
    set_registry(MetricsRegistry())
    bad = []

    # -- fixture: labeled dense-LR stream + warm model --------------------
    n_rows, dim, batch = 1536, 24, 128           # 12 micro-batches
    rng = np.random.RandomState(7)
    X = rng.randn(n_rows, dim)
    y = (X @ rng.randn(dim) + 0.3 * rng.randn(n_rows) > 0).astype(
        np.int64)
    vecs = np.empty(n_rows, object)
    vecs[:] = [DenseVector(X[i]) for i in range(n_rows)]
    tbl = MTable({"vec": vecs, "label": y}, "vec VECTOR, label LONG")
    warm = LogisticRegressionTrainBatchOp(
        vector_col="vec", label_col="label", max_iter=3).link_from(
        MemSourceBatchOp(tbl.first_n(256)))
    warm.get_output_table()

    def mkdag(art, **kw):
        return OnlineDag(
            source_fn=lambda: MemSourceStreamOp(tbl, batch_size=batch),
            warm_model=warm, artifacts_dir=art, label_col="label",
            vector_col="vec", time_interval=3.0, checkpoint_every=3,
            name="e2e_smoke", **kw)

    def eval_files(art):
        return (open(os.path.join(art, "eval", "windows.jsonl")).read(),
                open(os.path.join(art, "eval", "scores.jsonl")).read())

    def model_rows(art):
        with open(os.path.join(art, "serving", "last_good.json")) as f:
            return json.load(f)["rows"]

    # -- clean golden run -------------------------------------------------
    with scoped_fault_env(None):
        g_art = tempfile.mkdtemp(prefix="e2e_gold_")
        g_rep = mkdag(g_art).run()
    if g_rep.failed is not None:
        print(f"e2e_smoke: clean run FAILED: {g_rep.failed}",
              file=sys.stderr)
        return EXIT
    gold_files = eval_files(g_art)
    gold_model = model_rows(g_art)
    gold_scores = [json.loads(l) for l in gold_files[1].splitlines()]
    print(f"e2e_smoke: clean — {len(g_rep.windows)} windows, final AUC "
          f"{g_rep.final_window_auc:.3f}, {g_rep.swaps} swaps")

    # -- scenario 1: deterministic-recovery storm -------------------------
    def clear_trainer_kill(stage, exc):
        site = getattr(exc, "site", None)
        if site == "ftrl.batch":
            os.environ[FAULT_ENV] = ";".join(
                e for e in os.environ.get(FAULT_ENV, "").split(";")
                if e and not e.startswith("ftrl.batch"))

    with scoped_fault_env(STORM_DETERMINISTIC):
        s1_art = tempfile.mkdtemp(prefix="e2e_s1_")
        r1 = mkdag(s1_art, on_stage_event=clear_trainer_kill).run()
    if r1.failed is not None:
        bad.append(f"scenario 1 failed outright: {r1.failed}")
    else:
        sites = sorted(r.get("site") or r["error"] for r in r1.restarts)
        if sites != ["ckpt.save", "ftrl.batch"]:
            bad.append(f"scenario 1 expected ckpt.save + ftrl.batch "
                       f"restarts, got {r1.restarts}")
        for rec in r1.restarts:
            if rec["policy"] != "restart-from-last-checkpoint":
                bad.append(f"scenario 1 restart policy wrong: {rec}")
            if not rec.get("recovery_s"):
                bad.append(f"scenario 1 recovery time not measured: "
                           f"{rec}")
        if eval_files(s1_art) != gold_files:
            bad.append("scenario 1: eval windows/scores are NOT bitwise"
                       " identical to the clean run (the trainer did "
                       "not resume bitwise, or a micro-batch was "
                       "dropped/double-applied)")
        if model_rows(s1_art) != gold_model:
            bad.append("scenario 1: final model diverged from the "
                       "clean run")
        if r1.silent_drops:
            bad.append(f"scenario 1: {r1.silent_drops} SILENT drops")
        print(f"e2e_smoke: scenario 1 — {len(r1.restarts)} supervised "
              f"trainer restarts (recovery "
              f"{[r['recovery_s'] for r in r1.restarts]}s), journals "
              f"bitwise vs clean")

    # -- scenario 2: degraded serving storm + SLO verdicts ----------------
    slo2 = SloContract(serve_p99_s=1e-6,          # breaches BY DESIGN
                       swap_staleness_s=30.0,     # generous: stays ok
                       final_window_auc=0.6)      # held by last-good
    with scoped_fault_env(STORM_DEGRADED):
        s2_art = tempfile.mkdtemp(prefix="e2e_s2_")
        r2 = mkdag(s2_art, slo=slo2).run()
    if r2.failed is not None:
        bad.append(f"scenario 2 failed outright: {r2.failed}")
    else:
        if r2.feeder_skipped != 1:
            bad.append(f"scenario 2: corrupt snapshot not skipped "
                       f"exactly once (skipped={r2.feeder_skipped})")
        if r2.silent_drops:
            bad.append(f"scenario 2: {r2.silent_drops} SILENT drops")
        if not r2.typed_rejections:
            bad.append("scenario 2: the dispatch-error storm produced "
                       "no typed rejections (did it run?)")
        brk = r2.server_stats.get("breaker") or {}
        if not brk.get("opens"):
            bad.append("scenario 2: the error storm never opened the "
                       "breaker")
        if brk.get("state") != "closed":
            bad.append(f"scenario 2: breaker did not recover "
                       f"(state={brk.get('state')})")
        if not r2.server_stats.get("fallback_batches"):
            bad.append("scenario 2: no batch served through the "
                       "breaker fallback (degradation never engaged)")
        # zero torn + measured compiled recovery, value-level: every
        # served score within fallback-ulp tolerance of the clean run
        # (the corrupt snapshot holds the model ONE version back for a
        # while, so compare only batches before the skipped boundary
        # and after the next swap realigns the models: by construction
        # here, swap 2 realigns at t>=6 -> seq>=8)
        s2_scores = [json.loads(l)
                     for l in eval_files(s2_art)[1].splitlines()]
        if len(s2_scores) != len(gold_scores):
            bad.append(f"scenario 2: {len(s2_scores)} scored batches "
                       f"vs clean {len(gold_scores)}")
        else:
            # the final batch must be BITWISE the clean run's: the
            # breaker re-closed and the tail was served by the SAME
            # compiled programs on the SAME model — measured recovery
            if s2_scores[-1] != gold_scores[-1]:
                bad.append("scenario 2: final scored batch is not "
                           "bitwise the clean run's — the breaker did "
                           "not measurably recover to the compiled "
                           "path (or the model diverged)")
        if model_rows(s2_art) != gold_model:
            bad.append("scenario 2: final model diverged (serve-side "
                       "faults must not touch training)")
        # the SLO verdicts must MATCH the injected storm
        if not any(b.slo == "serve_p99" for b in r2.breaches):
            bad.append("scenario 2: no live serve_p99 breach recorded "
                       "under the armed 1us bound")
        by = {v.slo: v for v in r2.slo}
        if by["serve_p99"].ok:
            bad.append("scenario 2: final serve_p99 verdict ok under "
                       "a 1us bound (verdicts do not match the storm)")
        if not by["swap_staleness"].ok or not by["final_window_auc"].ok:
            bad.append(f"scenario 2: unbreached clauses flagged: "
                       f"{[v.to_dict() for v in r2.slo]}")
        print(f"e2e_smoke: scenario 2 — breaker opened "
              f"{brk.get('opens')}x and re-closed, "
              f"{r2.server_stats.get('fallback_batches')} degraded "
              f"batches, 1 poisoned snapshot skipped, "
              f"{r2.typed_rejections} typed rejections retried, SLO "
              f"verdicts match the storm")

    # -- scenario 3: latency + deadline shed leg --------------------------
    from alink_tpu.common.params import Params
    from alink_tpu.operator.common.linear.mapper import LinearModelMapper
    from alink_tpu.serving import CompiledPredictor, PredictServer
    from alink_tpu.serving.resilience import DeadlineExceeded
    import time as _time
    mapper = LinearModelMapper(
        warm.get_output_table().schema, tbl.select(["vec"]).schema,
        Params({"prediction_col": "pred", "vector_col": "vec"}))
    mapper.load_model(warm.get_output_table())
    pred = CompiledPredictor(mapper, name="e2e_shed")
    for b in pred.buckets:
        pred.predict_table(tbl.select(["vec"]).first_n(min(b, n_rows)))
    probe = tbl.select(["vec"]).row(0)
    tally = {"submitted": 0, "results": 0, "shed": 0, "typed": 0,
             "silent": 0}
    with scoped_fault_env(STORM_DELAY):
        srv = PredictServer(pred, name="e2e_shed")
        try:
            f_first = srv.submit(probe)     # occupies the slow dispatch
            tally["submitted"] += 1
            _time.sleep(0.01)
            futs = [srv.submit(probe, deadline_s=0.004)
                    for _ in range(6)]
            tally["submitted"] += 6
            for f in [f_first] + futs:
                try:
                    f.result(60)
                    tally["results"] += 1
                except DeadlineExceeded:
                    tally["shed"] += 1
                except TimeoutError:
                    tally["silent"] += 1
                except BaseException:
                    tally["typed"] += 1
        finally:
            srv.close()
    if tally["silent"]:
        bad.append(f"scenario 3: {tally['silent']} SILENT drops")
    if not tally["shed"]:
        bad.append("scenario 3: the latency+deadline leg shed nothing")
    if tally["results"] + tally["shed"] + tally["typed"] \
            != tally["submitted"]:
        bad.append(f"scenario 3 accounting broke: {tally}")
    print(f"e2e_smoke: scenario 3 — {tally['shed']} typed deadline "
          f"sheds, zero silent over {tally['submitted']} requests")

    if bad:
        print("e2e_smoke: FAILED:", file=sys.stderr)
        for m in bad:
            print(f"  {m}", file=sys.stderr)
        return EXIT
    print(f"e2e_smoke: clean — whole-loop storm held the SLO contract "
          f"(bitwise trainer resume, measured breaker recovery, typed "
          f"sheds, zero torn / zero silent)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
