from .base import (Pipeline, PipelineModel, PipelineStage, Estimator, Transformer,
                   Model, MapModel, Trainer, LocalPredictor)
from . import classification, regression
