"""Scaler / imputer operators (column family).

Re-design of common/dataproc/ StandardScaler, MinMaxScaler, MaxAbsScaler,
Imputer train/predict pairs (+ their ModelDataConverters): fit = one
summarizer pass; transform = vectorized column math.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from ....common.mtable import MTable
from ....common.params import InValidator, ParamInfo, Params
from ....common.types import AlinkTypes, TableSchema
from ....mapper.base import ModelMapper, OutputColsHelper
from ....model.converters import SimpleModelDataConverter, decode_array, encode_array
from ....params.shared import HasOutputCols, HasSelectedCols
from ...base import BatchOperator
from ...common.statistics.summarizer import summarize_table
from ..utils.model_map import ModelMapBatchOp


class _ColScalerModel:
    def __init__(self, kind: str, cols: List[str], stats: Dict[str, np.ndarray],
                 extra: Optional[Dict] = None):
        self.kind = kind
        self.cols = cols
        self.stats = stats      # name -> array of per-col constants
        self.extra = extra or {}


class _ColScalerConverter(SimpleModelDataConverter):
    def serialize_model(self, m: _ColScalerModel):
        meta = Params({"kind": m.kind, "cols": m.cols, **m.extra})
        return meta, [json.dumps({k: v.tolist() for k, v in m.stats.items()})]

    def deserialize_model(self, meta: Params, data):
        stats = {k: np.asarray(v, np.float64)
                 for k, v in json.loads(data[0]).items()}
        extra = {k: v for k, v in meta._m.items() if k not in ("kind", "cols")}
        return _ColScalerModel(meta._m["kind"], list(meta._m["cols"]), stats, extra)


class _ColScalerMapper(ModelMapper):
    def __init__(self, model_schema, data_schema, params=None, **kwargs):
        super().__init__(model_schema, data_schema, params, **kwargs)
        self.model: Optional[_ColScalerModel] = None

    def load_model(self, model_table: MTable):
        self.model = _ColScalerConverter().load_model(model_table)

    def get_output_schema(self) -> TableSchema:
        out_cols = self.params._m.get("output_cols") or self.model.cols
        return OutputColsHelper(self.data_schema, out_cols,
                                [AlinkTypes.DOUBLE] * len(out_cols)).get_output_schema()

    def map_table(self, data: MTable) -> MTable:
        m = self.model
        out_cols = self.params._m.get("output_cols") or m.cols
        outs = []
        for i, c in enumerate(m.cols):
            v = np.asarray(data.col(c), np.float64)
            outs.append(_transform_col(m, i, v))
        helper = OutputColsHelper(data.schema, out_cols,
                                  [AlinkTypes.DOUBLE] * len(out_cols))
        return helper.build_output(data, outs)


def _transform_col(m: _ColScalerModel, i: int, v: np.ndarray) -> np.ndarray:
    if m.kind == "standard":
        mean, std = m.stats["mean"][i], m.stats["std"][i]
        if not m.extra.get("with_mean", True):
            mean = 0.0
        if not m.extra.get("with_std", True):
            return v - mean
        return (v - mean) / (std if std > 0 else 1.0)
    if m.kind == "minmax":
        mn, mx = m.stats["min"][i], m.stats["max"][i]
        lo, hi = m.extra.get("min_out", 0.0), m.extra.get("max_out", 1.0)
        span = mx - mn
        scaled = (v - mn) / (span if span > 0 else 1.0)
        return scaled * (hi - lo) + lo
    if m.kind == "maxabs":
        ma = m.stats["maxabs"][i]
        return v / (ma if ma > 0 else 1.0)
    if m.kind == "imputer":
        fill = m.stats["fill"][i]
        return np.where(np.isnan(v), fill, v)
    raise ValueError(m.kind)


class _ColScalerTrainBase(BatchOperator, HasSelectedCols):
    KIND = ""

    def _fit_stats(self, t: MTable, cols: List[str]) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def _extra(self) -> Dict:
        return {}

    def link_from(self, in_op: BatchOperator):
        t = in_op.get_output_table()
        cols = self.get_selected_cols()
        if not cols:
            cols = [n for n, tp in zip(t.schema.names, t.schema.types)
                    if AlinkTypes.is_numeric(tp)]
        stats = self._fit_stats(t, cols)
        model = _ColScalerModel(self.KIND, cols, stats, self._extra())
        self._output = _ColScalerConverter().save_model(model)
        return self


class StandardScalerTrainBatchOp(_ColScalerTrainBase):
    """reference: dataproc/StandardScalerTrainBatchOp"""
    KIND = "standard"
    WITH_MEAN = ParamInfo("with_mean", bool, default=True)
    WITH_STD = ParamInfo("with_std", bool, default=True)

    def _fit_stats(self, t, cols):
        s = summarize_table(t, cols)
        return {"mean": np.asarray([s.mean(c) for c in cols]),
                "std": np.asarray([s.standard_deviation(c) for c in cols])}

    def _extra(self):
        return {"with_mean": self.get_with_mean(), "with_std": self.get_with_std()}


class MinMaxScalerTrainBatchOp(_ColScalerTrainBase):
    KIND = "minmax"
    MIN = ParamInfo("min_out", float, default=0.0, aliases=("min",))
    MAX = ParamInfo("max_out", float, default=1.0, aliases=("max",))

    def _fit_stats(self, t, cols):
        s = summarize_table(t, cols)
        return {"min": np.asarray([s.min(c) for c in cols]),
                "max": np.asarray([s.max(c) for c in cols])}

    def _extra(self):
        return {"min_out": self.get_min_out(), "max_out": self.get_max_out()}


class MaxAbsScalerTrainBatchOp(_ColScalerTrainBase):
    KIND = "maxabs"

    def _fit_stats(self, t, cols):
        s = summarize_table(t, cols)
        return {"maxabs": np.asarray([max(abs(s.min(c)), abs(s.max(c)))
                                      for c in cols])}


class ImputerTrainBatchOp(_ColScalerTrainBase):
    """reference: dataproc/ImputerTrainBatchOp (MEAN/MIN/MAX/VALUE strategies)"""
    KIND = "imputer"
    STRATEGY = ParamInfo("strategy", str, default="MEAN",
                         validator=InValidator(["MEAN", "MIN", "MAX", "VALUE"]))
    FILL_VALUE = ParamInfo("fill_value", float, default=0.0)

    def _fit_stats(self, t, cols):
        s = summarize_table(t, cols)
        strat = self.get_strategy().upper()
        if strat == "MEAN":
            fill = [s.mean(c) for c in cols]
        elif strat == "MIN":
            fill = [s.min(c) for c in cols]
        elif strat == "MAX":
            fill = [s.max(c) for c in cols]
        else:
            fill = [self.get_fill_value()] * len(cols)
        return {"fill": np.asarray(fill)}


class _ColScalerPredictBase(ModelMapBatchOp, HasOutputCols):
    MAPPER_CLS = _ColScalerMapper


class StandardScalerPredictBatchOp(_ColScalerPredictBase):
    pass


class MinMaxScalerPredictBatchOp(_ColScalerPredictBase):
    pass


class MaxAbsScalerPredictBatchOp(_ColScalerPredictBase):
    pass


class ImputerPredictBatchOp(_ColScalerPredictBase):
    pass
