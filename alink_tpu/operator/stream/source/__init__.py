from .sources import (BaseSourceStreamOp, BoundedTableStreamSource,
                      CsvSourceStreamOp, DBSourceStreamOp, LibSvmSourceStreamOp,
                      MemSourceStreamOp, MySqlSourceStreamOp,
                      NumSeqSourceStreamOp, RandomTableSourceStreamOp,
                      TableSourceStreamOp, TextSourceStreamOp)

__all__ = ["BaseSourceStreamOp", "BoundedTableStreamSource",
           "CsvSourceStreamOp", "DBSourceStreamOp", "LibSvmSourceStreamOp",
           "MemSourceStreamOp", "MySqlSourceStreamOp", "NumSeqSourceStreamOp",
           "RandomTableSourceStreamOp", "TableSourceStreamOp",
           "TextSourceStreamOp"]
