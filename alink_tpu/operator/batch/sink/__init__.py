from .sinks import (BaseSinkBatchOp, CsvSinkBatchOp, DBSinkBatchOp,
                    LibSvmSinkBatchOp, MemSinkBatchOp, MySqlSinkBatchOp,
                    TextSinkBatchOp)

__all__ = ["BaseSinkBatchOp", "CsvSinkBatchOp", "DBSinkBatchOp",
           "LibSvmSinkBatchOp", "MemSinkBatchOp", "MySqlSinkBatchOp",
           "TextSinkBatchOp"]
