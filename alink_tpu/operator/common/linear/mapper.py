"""LinearModelMapper — batched model serving.

Re-design of common/linear/LinearModelMapper.java (per-row dot product,
reference call stack §3.4) as a batched kernel: the whole input table is
encoded once and scored with one matmul.
"""

from __future__ import annotations

import json
from typing import List, Optional

import numpy as np

from ....common.mtable import MTable
from ....common.types import AlinkTypes, TableSchema
from ....mapper.base import ModelMapper, OutputColsHelper
from ..dataproc.feature_extract import extract_design
from .base import LinearModelData, LinearModelDataConverter, LinearModelType


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-np.clip(x, -500, 500)))


class LinearModelMapper(ModelMapper):
    def __init__(self, model_schema, data_schema, params=None, **kwargs):
        super().__init__(model_schema, data_schema, params, **kwargs)
        self.model: Optional[LinearModelData] = None

    def load_model(self, model_table: MTable):
        label_type = model_table.schema.types[2] if len(model_table.schema) > 2 \
            else AlinkTypes.STRING
        self.model = LinearModelDataConverter(label_type).load_model(model_table)

    # ------------------------------------------------------------------
    def _scores(self, data: MTable) -> np.ndarray:
        m = self.model
        design = extract_design(data, m.feature_names, m.vector_col,
                                np.float64, vector_size=m.vector_size)
        coef = m.coef
        if m.linear_model_type == LinearModelType.Softmax:
            k = len(m.label_values)
            W = coef.reshape(k - 1, -1)
            if m.has_intercept:
                b, Wf = W[:, 0], W[:, 1:]
            else:
                b, Wf = np.zeros(k - 1), W
            Z = _matmul(design, Wf.T, m.vector_size) + b
            return np.concatenate([Z, np.zeros((Z.shape[0], 1))], 1)
        if m.has_intercept:
            b, wf = coef[0], coef[1:]
        else:
            b, wf = 0.0, coef
        return _matmul(design, wf, m.vector_size) + b

    def predict_scores(self, data: MTable) -> np.ndarray:
        return self._scores(data)

    def get_output_schema(self) -> TableSchema:
        m = self.model
        pred_col = self.params._m.get("prediction_col", "pred")
        detail_col = self.params._m.get("prediction_detail_col")
        reserved = self.params._m.get("reserved_cols")
        regression = m.linear_model_type in LinearModelType.IS_REGRESSION if m else False
        out_type = AlinkTypes.DOUBLE if regression else (m.label_type if m else "STRING")
        cols, types = [pred_col], [out_type]
        if detail_col:
            cols.append(detail_col)
            types.append(AlinkTypes.STRING)
        return OutputColsHelper(self.data_schema, cols, types, reserved).get_output_schema()

    def map_table(self, data: MTable) -> MTable:
        m = self.model
        if m is None:
            raise RuntimeError("load_model must be called before map_table")
        pred_col = self.params._m.get("prediction_col", "pred")
        detail_col = self.params._m.get("prediction_detail_col")
        reserved = self.params._m.get("reserved_cols")
        scores = self._scores(data)
        out_cols, out_types = [], []
        details = None
        if m.linear_model_type in LinearModelType.IS_REGRESSION:
            preds = scores
            out_types = [AlinkTypes.DOUBLE]
        elif m.linear_model_type == LinearModelType.Softmax:
            e = np.exp(scores - scores.max(1, keepdims=True))
            probs = e / e.sum(1, keepdims=True)
            pick = probs.argmax(1)
            label_arr = np.empty(len(m.label_values), object)
            label_arr[:] = list(m.label_values)
            preds = _label_array(label_arr[pick])
            if detail_col:
                from ..evaluation.detail import PredictionDetailColumn
                details = PredictionDetailColumn(
                    [str(l) for l in m.label_values], probs)
            out_types = [m.label_type]
        else:
            label_arr = np.empty(2, object)
            label_arr[:] = [m.label_values[0], m.label_values[1]]
            # ~(s > 0), not (s <= 0): a NaN score must keep mapping to the
            # negative label as the per-row 'if s > 0' did
            preds = _label_array(label_arr[(~(scores > 0)).astype(np.intp)])
            if detail_col:
                from ..evaluation.detail import PredictionDetailColumn
                p_pos = _sigmoid(scores)
                details = PredictionDetailColumn(
                    [str(m.label_values[0]), str(m.label_values[1])],
                    np.stack([p_pos, 1.0 - p_pos], axis=1))
            out_types = [m.label_type]
        cols = [pred_col]
        values = [preds]
        if detail_col:
            cols.append(detail_col)
            out_types.append(AlinkTypes.STRING)
            values.append(details if details is not None
                          else np.asarray([None] * len(preds), object))
        helper = OutputColsHelper(data.schema, cols, out_types, reserved)
        return helper.build_output(data, values)


def _matmul(design, w, dim):
    if design["kind"] == "dense":
        return design["X"] @ w
    idx, val = design["idx"], design["val"]
    if w.ndim == 1:
        return (val * w[idx]).sum(-1)
    # (n, nnz, k)
    return (val[..., None] * w[idx]).sum(1)


def _label_array(values: List) -> np.ndarray:
    first = values[0] if len(values) else ""
    if isinstance(first, (int, np.integer)):
        return np.asarray(values, np.int64)
    if isinstance(first, (float, np.floating)):
        return np.asarray(values, np.float64)
    out = np.empty(len(values), object)
    out[:] = values
    return out
