"""tools/run_report.py CLI — dump round-trip + rendered table contents.

The report renderer is the operator-facing surface of the metrics
subsystem; these tests pin the section layout and the actual numbers a
known registry dump renders to (not just "exit code 0"), plus the
--prom / --all / --trace modes.
"""

import importlib.util
import json
import os

import pytest

from alink_tpu.common.metrics import MetricsRegistry
from alink_tpu.common.tracing import Tracer

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_run_report():
    spec = importlib.util.spec_from_file_location(
        "run_report_under_test", os.path.join(ROOT, "tools", "run_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _populated_registry() -> MetricsRegistry:
    """A registry shaped like a real run: engine, collectives, spans,
    stream, FTRL, batch ops, and one uncovered extra."""
    reg = MetricsRegistry()
    reg.inc("alink_comqueue_execs_total", 2)
    reg.inc("alink_comqueue_supersteps_total", 10)
    reg.inc("alink_comqueue_program_cache_total", 1, {"result": "hit"})
    reg.inc("alink_comqueue_program_cache_total", 1, {"result": "miss"})
    ar = {"collective": "AllReduce"}
    reg.inc("alink_collective_calls_total", 10, ar)
    reg.inc("alink_collective_logical_bytes_total", 320, ar)
    reg.observe("alink_step_timer_seconds", 0.137,
                {"span": "comqueue.execute", "program": "miss"})
    reg.observe("alink_stream_batch_seconds", 0.004, {"op": "SelectStreamOp"})
    reg.inc("alink_stream_batches_total", 5, {"op": "SelectStreamOp"})
    reg.inc("alink_stream_rows_total", 40, {"op": "SelectStreamOp"})
    reg.observe("alink_ftrl_batch_seconds", 0.002,
                {"op": "FtrlTrainStreamOp", "mode": "batch"})
    reg.inc("alink_ftrl_rows_total", 1000,
            {"op": "FtrlTrainStreamOp", "mode": "batch"})
    reg.observe("alink_batch_op_seconds", 0.05, {"op": "SelectBatchOp"})
    reg.inc("alink_batch_rows_in_total", 10, {"op": "SelectBatchOp"})
    reg.inc("alink_batch_rows_out_total", 10, {"op": "SelectBatchOp"})
    reg.set_gauge("alink_program_flops", 1234.0, {"program": "qn"})
    return reg


@pytest.fixture
def dump_path(tmp_path):
    return _populated_registry().dump(str(tmp_path / "run.jsonl"))


class TestRunReportCli:
    def test_dump_round_trips_before_rendering(self, dump_path):
        reg = _populated_registry()
        loaded = MetricsRegistry.load(dump_path)
        assert loaded.snapshot() == reg.snapshot()

    def test_rendered_tables_carry_the_numbers(self, dump_path, capsys):
        mod = _load_run_report()
        assert mod.main([dump_path]) == 0
        out = capsys.readouterr().out
        # run summary: totals and the derived rates
        assert "== Run summary ==" in out
        assert "comqueue execs" in out and "supersteps" in out
        assert "50.0%" in out            # 1 hit / (1 hit + 1 miss)
        assert "5.0" in out              # supersteps / exec
        # collectives: calls, formatted bytes, bytes/call
        assert "AllReduce" in out and "320 B" in out and "32 B" in out
        # host spans with merged extra labels
        assert "comqueue.execute [program=miss]" in out
        # stream throughput: 40 rows / 0.004 s = 10,000 rows/s
        assert "SelectStreamOp" in out and "10,000" in out
        # FTRL section with its mode label
        assert "== FTRL ==" in out and "mode=batch" in out
        # batch ops
        assert "SelectBatchOp" in out
        # the uncovered gauge falls through to Other metrics
        assert "== Other metrics ==" in out
        assert "alink_program_flops" in out and "program=qn" in out

    def test_all_flag_lists_claimed_series_too(self, dump_path, capsys):
        mod = _load_run_report()
        assert mod.main([dump_path, "--all"]) == 0
        out = capsys.readouterr().out
        # --all repeats section-claimed metrics under Other metrics
        assert "alink_comqueue_execs_total" in out

    def test_prom_mode_emits_exposition_text(self, dump_path, capsys):
        mod = _load_run_report()
        assert mod.main([dump_path, "--prom"]) == 0
        out = capsys.readouterr().out
        assert "# TYPE alink_comqueue_execs_total counter" in out
        assert 'alink_collective_calls_total{collective="AllReduce"} 10.0' \
            in out

    def test_trace_flag_appends_span_summary(self, dump_path, tmp_path,
                                             capsys):
        tr = Tracer()
        with tr.span("comqueue.exec", cat="engine"):
            with tr.span("comqueue.execute", cat="steptimer"):
                pass
            tr.instant("comqueue.program_cache", args={"result": "hit"})
        tp = tr.export_jsonl(str(tmp_path / "trace.jsonl"))
        mod = _load_run_report()
        assert mod.main([dump_path, "--trace", tp]) == 0
        out = capsys.readouterr().out
        # metrics tables AND the trace rollup in one report
        assert "== Run summary ==" in out
        assert "== Trace summary ==" in out
        assert "== Top spans by self time" in out
        assert "comqueue.program_cache" in out

    def test_prom_mode_never_appends_trace_tables(self, dump_path,
                                                  tmp_path, capsys):
        tr = Tracer()
        with tr.span("s"):
            pass
        tp = tr.export_jsonl(str(tmp_path / "trace.jsonl"))
        mod = _load_run_report()
        assert mod.main([dump_path, "--prom", "--trace", tp]) == 0
        out = capsys.readouterr().out
        # stdout stays pure Prometheus exposition text
        assert "Trace summary" not in out
        assert "# TYPE alink_comqueue_execs_total counter" in out

    def test_empty_registry_renders(self, tmp_path, capsys):
        p = MetricsRegistry().dump(str(tmp_path / "empty.jsonl"))
        mod = _load_run_report()
        assert mod.main([p]) == 0
        out = capsys.readouterr().out
        assert "(none)" in out

    def test_run_dir_accepted_with_sibling_artifacts(self, tmp_path,
                                                     capsys):
        """A bench.py --run-dir directory is a valid report target: the
        metrics dump inside is the report, a sibling trace.jsonl
        auto-attaches, and a profile.json earns a pointer at
        tools/doctor.py (the profile has its own renderer)."""
        d = tmp_path / "run"
        d.mkdir()
        _populated_registry().dump(str(d / "metrics.jsonl"))
        tr = Tracer()
        with tr.span("comqueue.exec", cat="engine"):
            pass
        tr.export_jsonl(str(d / "trace.jsonl"))
        with open(d / "profile.json", "w") as f:
            json.dump({"format": "alink_tpu_profile_v1"}, f)
        mod = _load_run_report()
        assert mod.main([str(d)]) == 0
        out = capsys.readouterr().out
        assert "== Run summary ==" in out
        assert "== Trace summary ==" in out          # auto-attached
        assert "tools/doctor.py" in out              # profile pointer

    def test_run_dir_without_metrics_exits_1(self, tmp_path, capsys):
        d = tmp_path / "empty_dir"
        d.mkdir()
        mod = _load_run_report()
        assert mod.main([str(d)]) == 1
        assert "no metrics.jsonl" in capsys.readouterr().err
