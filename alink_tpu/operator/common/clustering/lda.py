"""LDA core kernels — TPU-native online-variational + batched EM training.

Re-design of the reference LDA internals
(operator/common/clustering/lda/: OnlineCorpusStep.java,
UpdateLambdaAndAlpha.java, EmCorpusStep.java, EmLogLikelihood.java,
BuildOnlineLdaModel.java, BuildEmLdaModel.java; driven from
operator/batch/clustering/LdaTrainBatchOp.java:132-190).

TPU-first changes vs the reference:

* Corpus representation: padded ``(n_docs, max_len)`` token-id + count
  arrays (bag-of-words per doc, zero-count padding) instead of per-row
  ``SparseVector``s — static shapes for XLA, docs partition-resident on
  devices across supersteps.
* Online method = Hoffman-style stochastic variational inference. The
  per-minibatch E-step is a fixed-trip ``lax.fori_loop`` of *batched*
  digamma/softmax updates where the hot contractions
  (``expElogtheta @ expElogbeta[:, ids]``) are einsums on the MXU; the
  reference's per-document Java loops (OnlineCorpusStep.java) have no
  analogue. Sufficient stats are scatter-added with ``segment_sum`` and
  combined across workers with one ``psum`` (replacing
  ``AllReduce(wordTopicStat)``).
* EM method: the reference uses collapsed Gibbs sampling
  (EmCorpusStep.java) — a per-token sequential sampler that is hostile to
  a systolic array. We train the same model shape (the ``gamma``
  word-topic count matrix incl. a trailing topic-total row,
  LdaModelData.java ``gamma``) with batched variational EM: per-superstep
  document E-step (doc-topic responsibilities) + psum'd expected
  word-topic counts. Deterministic, matmul-shaped, same predict formulas.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ....engine import IterativeComQueue
from ..nlp.text import _tokens


def encode_corpus(texts, index: dict, max_len: Optional[int] = None,
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Texts -> padded (n, L) word-id and count arrays (bag of words).

    Out-of-vocabulary tokens are dropped (reference Document2Vector via
    DocCountVectorizerModelMapper). Tokenization is the shared ``_tokens``
    (the same one ``train_doc_count_vectorizer`` builds the vocab with).
    Padding has count 0 and id 0.
    """
    docs = []
    for t in texts:
        toks = _tokens(t)
        bag = {}
        for w in toks:
            i = index.get(w)
            if i is not None:
                bag[i] = bag.get(i, 0.0) + 1.0
        docs.append(sorted(bag.items()))
    L = max_len or max((len(d) for d in docs), default=1)
    L = max(L, 1)
    n = len(docs)
    ids = np.zeros((n, L), np.int32)
    cnts = np.zeros((n, L), np.float64)
    for r, d in enumerate(docs):
        for c, (i, v) in enumerate(d[:L]):
            ids[r, c] = i
            cnts[r, c] = v
    return ids, cnts


def _e_step(ids, cnts, expElogbeta, alpha, key, n_inner: int = 50):
    """Batched variational E-step for one doc block.

    Returns (gamma (n,k), sstats (k,V)) where sstats already includes the
    expElogbeta factor (Hoffman'10 eq. 5 trick).
    """
    n, L = ids.shape
    k, V = expElogbeta.shape
    # (n, L, k): exp(E[log beta_{k, w_{nl}}])
    eb = jnp.take(expElogbeta.T, ids, axis=0)
    gamma0 = jax.random.gamma(key, 100.0, (n, k)) * 0.01

    def body(_, gamma):
        elt = jax.scipy.special.digamma(gamma) - \
            jax.scipy.special.digamma(gamma.sum(1, keepdims=True))
        expElt = jnp.exp(elt)
        phinorm = jnp.einsum("nk,nlk->nl", expElt, eb) + 1e-100
        return alpha + expElt * jnp.einsum("nl,nlk->nk", cnts / phinorm, eb)

    gamma = jax.lax.fori_loop(0, n_inner, body, gamma0)
    elt = jax.scipy.special.digamma(gamma) - \
        jax.scipy.special.digamma(gamma.sum(1, keepdims=True))
    expElt = jnp.exp(elt)
    phinorm = jnp.einsum("nk,nlk->nl", expElt, eb) + 1e-100
    contrib = (cnts / phinorm)[:, :, None] * expElt[:, None, :]   # (n, L, k)
    sstats = jax.ops.segment_sum(contrib.reshape(n * L, k), ids.reshape(-1),
                                 num_segments=V)                   # (V, k)
    return gamma, sstats.T * expElogbeta


def _bound_score(ids, cnts, gamma, beta_norm):
    """Per-block corpus log-likelihood proxy: sum c * log(theta . beta_w)."""
    theta = gamma / jnp.maximum(gamma.sum(1, keepdims=True), 1e-100)
    bw = jnp.take(beta_norm.T, ids, axis=0)                        # (n, L, k)
    pw = jnp.einsum("nk,nlk->nl", theta, bw)
    return (cnts * jnp.log(jnp.maximum(pw, 1e-100))).sum()


def _expElogbeta(lam):
    el = jax.scipy.special.digamma(lam) - \
        jax.scipy.special.digamma(lam.sum(1, keepdims=True))
    return jnp.exp(el)


def online_lda_train(ids: np.ndarray, cnts: np.ndarray, k: int, V: int,
                     num_iter: int = 10, alpha: float = -1.0, beta: float = -1.0,
                     tau0: float = 1024.0, kappa: float = 0.51,
                     subsample: float = 0.05, optimize_alpha: bool = True,
                     seed: int = 0, env=None, n_inner: int = 50):
    """Distributed online variational LDA (reference OnlineCorpusStep +
    UpdateLambdaAndAlpha on IterativeComQueue, LdaTrainBatchOp.java:176-190).

    Each superstep every worker samples ``subsample`` of its resident doc
    shard, runs the batched E-step, and the psum'd sufficient stats drive
    one natural-gradient lambda update with rho_t = (tau0+t)^-kappa.
    Returns (lambda (k,V), alpha (k,), loglik, log_perplexity).
    """
    if alpha <= 0:
        alpha = 1.0 / k
    if beta <= 0:
        beta = 1.0 / k
    n_total = ids.shape[0]
    rng = np.random.RandomState(seed)
    lam0 = rng.gamma(100.0, 1.0 / 100.0, (k, V))
    total_words = float(cnts.sum())

    def stage(ctx):
        if ctx.is_init_step:
            ctx.put_obj("lambda", jnp.asarray(lam0))
            ctx.put_obj("alpha_vec", jnp.full((k,), alpha))
            ctx.put_obj("score", jnp.zeros(()))
        ids_b = ctx.get_obj("ids")
        cnt_b = ctx.get_obj("cnts")
        lam = ctx.get_obj("lambda")
        avec = ctx.get_obj("alpha_vec")
        step = ctx.step_no
        key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        key = jax.random.fold_in(key, ctx.task_id)
        ksel, kgam = jax.random.split(key)
        sel = jax.random.uniform(ksel, (ids_b.shape[0],)) < subsample
        cnt_mb = jnp.where(sel[:, None], cnt_b, 0.0)
        eEb = _expElogbeta(lam)
        gamma, sstats = _e_step(ids_b, cnt_mb, eEb, avec[None, :], kgam, n_inner)
        mb_words = ctx.all_reduce_sum(cnt_mb.sum())
        sstats = ctx.all_reduce_sum(sstats)
        # materialize after BOTH registered: under fusion the word-count
        # scalar and the sufficient-statistics matrix ride ONE flattened
        # psum (2 -> 1); eagerly the asarray is a no-op
        mb_words, sstats = jnp.asarray(mb_words), jnp.asarray(sstats)
        # natural-gradient step, rescaled minibatch -> corpus
        rho = (tau0 + step) ** (-kappa)
        scale = total_words / jnp.maximum(mb_words, 1.0)
        lam_new = (1.0 - rho) * lam + rho * (beta + scale * sstats)
        ctx.put_obj("lambda", lam_new)
        # alpha update: Newton step on the Dirichlet MLE over minibatch gammas.
        # Mask out zero-count rows: comqueue zero-pads doc shards to a
        # multiple of the worker count, and padded (or genuinely empty)
        # docs carry no evidence — their gamma == alpha would bias the MLE
        # toward self-consistency with the current value.
        if optimize_alpha:
            valid = sel & (cnt_b.sum(1) > 0)
            n_sel = ctx.all_reduce_sum(valid.sum() * 1.0)
            elt = jax.scipy.special.digamma(gamma) - \
                jax.scipy.special.digamma(gamma.sum(1, keepdims=True))
            logphat_sum = ctx.all_reduce_sum((elt * valid[:, None]).sum(0))
            # both registered -> one fused psum under the flag
            n_sel = jnp.asarray(n_sel)
            logphat = jnp.asarray(logphat_sum) / jnp.maximum(n_sel, 1.0)
            grad = n_sel * (jax.scipy.special.digamma(avec.sum())
                            - jax.scipy.special.digamma(avec) + logphat)
            q = -n_sel * jax.scipy.special.polygamma(1, avec)
            z = n_sel * jax.scipy.special.polygamma(1, avec.sum())
            b = (grad / q).sum() / (1.0 / z + (1.0 / q).sum())
            # reject the step if any component would go non-positive OR the
            # minibatch was empty (n_sel=0 makes q=-0 -> b=NaN)
            danger = ((avec - rho * (grad - b) / q) <= 0).any() | (n_sel < 1)
            avec_new = jnp.where(danger, avec, avec - rho * (grad - b) / q)
            ctx.put_obj("alpha_vec", avec_new)
        # corpus bound: score the *fitted* minibatch docs and scale to the
        # corpus (the standard SVI estimate) — unselected docs' gamma is
        # just the prior, so scoring the full shard with it would be noise
        beta_norm = lam_new / jnp.maximum(lam_new.sum(1, keepdims=True), 1e-100)
        ctx.put_obj("score", ctx.all_reduce_sum(
            _bound_score(ids_b, cnt_mb, gamma, beta_norm)) * scale)

    q = (IterativeComQueue(env=env, max_iter=max(num_iter, 1), seed=seed)
         .init_with_partitioned_data("ids", ids)
         .init_with_partitioned_data("cnts", cnts)
         .add(stage)
         # total_words is a data-derived constant baked into the trace;
         # lam0 derives from (seed, k, V) and seed rides the engine key
         .set_program_key(("lda_online", k, V, float(alpha), float(beta),
                           float(tau0), float(kappa), float(subsample),
                           bool(optimize_alpha), int(n_inner), total_words)))
    res = q.exec()
    lam = res.get("lambda")
    avec = res.get("alpha_vec")
    score = float(res.get("score"))
    log_perp = -score / max(total_words, 1.0)
    return np.asarray(lam), np.asarray(avec), score, log_perp


def em_lda_train(ids: np.ndarray, cnts: np.ndarray, k: int, V: int,
                 num_iter: int = 10, alpha: float = -1.0, beta: float = -1.0,
                 seed: int = 0, env=None, n_inner: int = 20):
    """Distributed full-batch EM (stands in for the reference's collapsed
    Gibbs EmCorpusStep.java — see module docstring for why).

    Per superstep: batched doc E-step against the current word-topic
    counts, then psum of expected counts rebuilds the global ``gamma``
    matrix. Doc-topic state stays partition-resident in the carry (the
    analogue of the reference's per-task topic assignments cached in
    SessionSharedObjs). Returns (wordTopicCounts (V,k), topicCounts (k,),
    alpha, beta, loglik, log_perplexity).

    alpha/beta here are the *actual* Dirichlet priors (the reference's
    Gibbs path shifts its defaults by +1 for the collapsed predictive
    rule, LdaTrainBatchOp.java:118-124; variational EM needs no shift —
    the same values are reused untouched at predict time).
    """
    if alpha <= 0:
        alpha = 50.0 / k
    if beta <= 0:
        beta = 0.01
    rng = np.random.RandomState(seed)
    wt0 = rng.gamma(100.0, 1.0 / 100.0, (k, V))
    total_words = float(cnts.sum())

    def stage(ctx):
        if ctx.is_init_step:
            ctx.put_obj("wt", jnp.asarray(wt0))
            ctx.put_obj("score", jnp.zeros(()))
        ids_b = ctx.get_obj("ids")
        cnt_b = ctx.get_obj("cnts")
        wt = ctx.get_obj("wt")
        # point-estimate topics with beta smoothing — the same formula
        # LdaModelData.word_topic_probs applies at predict time
        beta_hat = (wt + beta) / (wt.sum(1, keepdims=True) + V * beta)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), ctx.task_id)
        gamma, _ = _e_step(ids_b, cnt_b, beta_hat, alpha, key, n_inner)
        # expected word-topic counts: phi ~ theta_k * beta_kw
        theta = gamma / jnp.maximum(gamma.sum(1, keepdims=True), 1e-100)
        eb = jnp.take(beta_hat.T, ids_b, axis=0)                  # (n, L, k)
        phi = theta[:, None, :] * eb
        phi = phi / jnp.maximum(phi.sum(-1, keepdims=True), 1e-100)
        contrib = cnt_b[:, :, None] * phi
        n, L = ids_b.shape
        wt_new = jax.ops.segment_sum(contrib.reshape(n * L, k),
                                     ids_b.reshape(-1), num_segments=V).T
        ctx.put_obj("wt", ctx.all_reduce_sum(wt_new))
        ctx.put_obj("score", ctx.all_reduce_sum(
            _bound_score(ids_b, cnt_b, gamma, beta_hat)))

    q = (IterativeComQueue(env=env, max_iter=max(num_iter, 1), seed=seed)
         .init_with_partitioned_data("ids", ids)
         .init_with_partitioned_data("cnts", cnts)
         .add(stage)
         .set_program_key(("lda_em", k, V, float(alpha), float(beta),
                           int(n_inner))))
    res = q.exec()
    wt = np.asarray(res.get("wt"))                                # (k, V)
    score = float(res.get("score"))
    log_perp = -score / max(total_words, 1.0)
    return wt.T, wt.sum(1), alpha, beta, score, log_perp


def expand_tokens(ids: np.ndarray, cnts: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Bag-of-words (ids, counts) -> per-OCCURRENCE token arrays.

    Collapsed Gibbs assigns a topic per token occurrence, not per bag
    entry; a count-c entry expands to c slots. Returns (tok (n, T) int32,
    mask (n, T) {0,1} f32) with zero padding, T = longest doc — never
    truncated, so counts are conserved exactly (the Gibbs invariant)."""
    n = ids.shape[0]
    docs = []
    for r in range(n):
        row = np.repeat(ids[r], cnts[r].astype(np.int64))
        docs.append(row)
    T = max(max((len(d) for d in docs), default=1), 1)
    tok = np.zeros((n, T), np.int32)
    mask = np.zeros((n, T), np.float32)
    for r, d in enumerate(docs):
        tok[r, :len(d)] = d
        mask[r, :len(d)] = 1.0
    return tok, mask


def gibbs_lda_train(ids: np.ndarray, cnts: np.ndarray, k: int, V: int,
                    num_iter: int = 50, alpha: float = -1.0,
                    beta: float = -1.0, seed: int = 0, env=None):
    """Distributed collapsed-Gibbs LDA — the TPU shape of the reference's
    EmCorpusStep (LdaTrainBatchOp.java:135; VERDICT r2 #7).

    The reference's sampler walks tokens sequentially, updating global
    counts token by token — hostile to a systolic array. The TPU-native
    equivalent is the standard distributed approximation (AD-LDA,
    Newman et al. JMLR'09) with Jacobi-style within-worker updates:

    * per-token topic assignments ``z`` live DEVICE-RESIDENT in the
      superstep carry, sharded with the doc partition (the analogue of
      the reference's per-task topic arrays in SessionSharedObjs);
    * each superstep rebuilds doc-topic counts ``nd`` (one-hot einsum),
      word-topic counts ``nw`` (scatter-add, ``lax.psum`` across
      workers — the reference's AllReduce of wordTopicStat), subtracts
      each token's OWN contribution, and samples every token in
      parallel with ``jax.random.categorical`` over the collapsed
      posterior (nd-z+alpha)*(nw-z+beta)/(nt-z+V*beta);
    * counts re-psum next superstep, so cross-worker staleness is one
      superstep — exactly AD-LDA's approximation.

    Default priors mirror the reference Gibbs path INCLUDING its +1
    shift (alpha=50/k+1, beta=0.01+1, LdaTrainBatchOp.java:118-124);
    explicitly-passed alpha/beta are used as given in the collapsed
    rule. Returns (wordTopicCounts
    (V, k), topicCounts (k,), alpha, beta, loglik, log_perplexity).
    """
    if alpha <= 0:
        alpha = 50.0 / k + 1.0
    if beta <= 0:
        beta = 0.01 + 1.0
    tok, mask = expand_tokens(ids, cnts)
    n, T = tok.shape
    total_words = float(mask.sum())
    rng = np.random.RandomState(seed)
    z0 = rng.randint(0, k, size=(n, T)).astype(np.int32)

    def stage(ctx):
        if ctx.is_init_step:
            ctx.put_obj("z", ctx.get_obj("z_init"))
        tok_b = ctx.get_obj("tok")
        mask_b = ctx.get_obj("mask")
        z = ctx.get_obj("z")
        oh = jax.nn.one_hot(z, k, dtype=jnp.float32) * mask_b[..., None]
        nd = oh.sum(1)                                         # (n, k)
        # word-topic counts: scatter over flat (topic, word) cells
        flat = (z.astype(jnp.int32) * V + tok_b).reshape(-1)
        nw = jnp.zeros((k * V,), jnp.float32).at[flat].add(
            mask_b.reshape(-1)).reshape(k, V)
        nw = ctx.all_reduce_sum(nw)                            # psum
        nt = nw.sum(1)                                         # (k,)
        # per-token posterior with own contribution removed (collapsed rule)
        nd_m = nd[:, None, :] - oh                             # (n, T, k)
        nw_tok = jnp.take(nw.T, tok_b, axis=0) - oh            # (n, T, k)
        nt_m = nt[None, None, :] - oh                          # (n, T, k)
        logp = (jnp.log(nd_m + alpha) + jnp.log(nw_tok + beta)
                - jnp.log(nt_m + V * beta))
        key = jax.random.fold_in(jax.random.PRNGKey(seed), ctx.step_no)
        key = jax.random.fold_in(key, ctx.task_id)
        z_new = jax.random.categorical(key, logp, axis=-1).astype(jnp.int32)
        z_new = jnp.where(mask_b > 0, z_new, 0)
        ctx.put_obj("z", z_new)

    q = (IterativeComQueue(env=env, max_iter=max(num_iter, 1), seed=seed)
         .init_with_partitioned_data("tok", tok)
         .init_with_partitioned_data("mask", mask)
         .init_with_partitioned_data("z_init", z0)
         .add(stage)
         .set_program_key(("lda_gibbs", k, V, float(alpha), float(beta))))
    res = q.exec()
    # final global counts from the final assignments (all shards)
    z_fin = res.concat("z", total=n)
    nw = np.zeros((k, V), np.float64)
    np.add.at(nw.reshape(-1), (z_fin.astype(np.int64) * V
                               + tok).reshape(-1)[mask.reshape(-1) > 0], 1.0)
    # score recomputed from the FINAL assignments so the reported
    # perplexity matches the returned counts (the in-carry score is one
    # superstep stale: it is computed from the counts before the last
    # resample)
    nd = np.zeros((n, k), np.float64)
    np.add.at(nd.reshape(-1), (np.arange(n)[:, None] * k
                               + z_fin).reshape(-1)[mask.reshape(-1) > 0], 1.0)
    theta = (nd + alpha) / (nd.sum(1, keepdims=True) + k * alpha)
    beta_hat = (nw + beta) / (nw.sum(1, keepdims=True) + V * beta)
    # chunk over docs: beta_hat.T[tok] for the whole corpus would be an
    # (n, T, k) float64 allocation
    score = 0.0
    for s0 in range(0, n, 2048):
        sl = slice(s0, min(s0 + 2048, n))
        pw = np.einsum("nk,ntk->nt", theta[sl], beta_hat.T[tok[sl]])
        score += float((mask[sl] * np.log(np.maximum(pw, 1e-100))).sum())
    log_perp = -score / max(total_words, 1.0)
    return nw.T, nw.sum(1), alpha, beta, score, log_perp


def lda_infer(ids: np.ndarray, cnts: np.ndarray, word_topic: np.ndarray,
              alpha, n_inner: int = 50, seed: int = 0) -> np.ndarray:
    """Doc-topic inference at predict time (reference LdaUtil /
    LdaModelMapper.predictResultDetail). word_topic: (V, k) p(w|z) columns
    (already normalized). Returns theta (n, k)."""
    from ....engine.comqueue import lazy_jit
    eEb = jnp.asarray(word_topic.T)                               # (k, V)
    alpha = jnp.asarray(alpha)
    key = jax.random.PRNGKey(seed)
    gamma, _ = lazy_jit(_e_step, static_argnums=(5,))(
        jnp.asarray(ids), jnp.asarray(cnts), eEb,
        alpha[None, :] if alpha.ndim == 1 else alpha, key, n_inner)
    gamma = np.asarray(gamma)
    return gamma / np.maximum(gamma.sum(1, keepdims=True), 1e-100)
