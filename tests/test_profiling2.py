"""Measured device profiling (common/profiling2.py) — Layer 3.

The contract under test: with ``ALINK_TPU_PROFILE`` OFF nothing changes
(lowered HLO byte-identical, program-cache keys untouched — toggling
the flag must HIT the cache, not recompile); with it ON the collector
attributes measured wall time across dispatch/transfer/device/
collective buckets, honors the read-only ``ComQueueResult`` memo
contract, measures live HBM at boundaries, verifies donation by
measurement, and the xprof parser ingests device-lane traces (host-only
traces fall back to the timing harness, returning None).
"""

import gzip
import json
import os
import time

import numpy as np
import pytest

from alink_tpu.common import profiling2 as p2
from alink_tpu.common.profiling2 import (ProfileCollector, donation_probe,
                                         measured_bound, parse_xprof_trace,
                                         profile_window, set_profiler)


@pytest.fixture
def collector(monkeypatch):
    """A fresh process collector with the flag ON (restored after)."""
    monkeypatch.setenv("ALINK_TPU_PROFILE", "1")
    monkeypatch.delenv("ALINK_TPU_PROFILE_DIR", raising=False)
    monkeypatch.delenv("ALINK_TPU_PROFILE_XPROF", raising=False)
    col = ProfileCollector()
    prev = set_profiler(col)
    yield col
    set_profiler(prev)


def _queue(env, n=16, max_iter=3, key=("p2test",)):
    from alink_tpu.engine import AllReduce, IterativeComQueue

    def stage(ctx):
        import jax.numpy as jnp
        if ctx.is_init_step:
            ctx.put_obj("acc", jnp.zeros(4))
        ctx.put_obj("acc", ctx.get_obj("acc") + ctx.get_obj("xs").sum(0))

    return (IterativeComQueue(env=env, max_iter=max_iter)
            .init_with_partitioned_data("xs", np.ones((n, 4), np.float32))
            .add(stage).add(AllReduce("acc"))
            .set_program_key(key))


def _env():
    from alink_tpu.common.mlenv import MLEnvironmentFactory
    return MLEnvironmentFactory.get_default()


class TestOffPathInvariance:
    def test_lowered_hlo_byte_identical_on_off(self, monkeypatch):
        monkeypatch.delenv("ALINK_TPU_PROFILE", raising=False)
        off = _queue(_env()).lowered().as_text()
        monkeypatch.setenv("ALINK_TPU_PROFILE", "1")
        on = _queue(_env()).lowered().as_text()
        assert off == on

    def test_toggling_flag_hits_program_cache(self, collector, monkeypatch):
        """The flag must NOT ride the program-cache key: an exec with
        profiling on reuses the program compiled with it off."""
        from alink_tpu.engine.comqueue import (clear_program_cache,
                                               program_cache_stats)
        clear_program_cache()
        monkeypatch.delenv("ALINK_TPU_PROFILE", raising=False)
        key = ("p2cache", time.time())   # unique per test run
        _queue(_env(), key=key).exec()
        s0 = program_cache_stats()
        monkeypatch.setenv("ALINK_TPU_PROFILE", "1")
        _queue(_env(), key=key).exec()
        s1 = program_cache_stats()
        assert s1["misses"] == s0["misses"]
        assert s1["hits"] == s0["hits"] + 1

    def test_capture_window_adds_zero_compiled_ops(self, collector):
        """An exec under an armed profile window lowers to the same HLO
        a bare exec does (the window wraps the already-compiled call)."""
        txt_profiled = _queue(_env()).lowered().as_text()
        os.environ.pop("ALINK_TPU_PROFILE", None)
        try:
            txt_plain = _queue(_env()).lowered().as_text()
        finally:
            os.environ["ALINK_TPU_PROFILE"] = "1"
        assert txt_profiled == txt_plain


class TestCollector:
    def test_marks_aggregate_and_measured_filtering(self, collector):
        with collector.workload("wl"):
            # unmeasured (warmup) mark — must NOT reach the attribution
            with profile_window("scope.a") as w:
                w.dispatch(5.0)
            with collector.measured_region():
                with profile_window("scope.a") as w:
                    w.dispatch(0.2, n=2)
                    w.device(0.1)
                    w.transfer(0.05, nbytes=123)
                    w.collective(0.01, calls=3)
        attr = collector.workload_attribution("wl")
        assert attr["dispatch_s"] == pytest.approx(0.2)
        assert attr["device_s"] == pytest.approx(0.1)
        assert attr["transfer_s"] == pytest.approx(0.05)
        assert attr["collective_s"] == pytest.approx(0.01)
        assert attr["dispatch_calls"] == 2
        assert attr["transfer_bytes"] == 123
        assert attr["measured_wall_s"] > 0
        assert attr["source"] == "timing-harness"

    def test_host_residual_is_wall_minus_marks(self, collector):
        with collector.workload("wl2"):
            with collector.measured_region():
                time.sleep(0.05)
                with profile_window("s") as w:
                    w.dispatch(0.01)
        attr = collector.workload_attribution("wl2")
        assert attr["host_s"] >= 0.03
        assert attr["host_s"] <= attr["measured_wall_s"]

    def test_unknown_workload_returns_none(self, collector):
        assert collector.workload_attribution("nope") is None

    def test_device_scopes_listed_per_leg(self, collector):
        """Attribution names which legs the device time came from —
        consumers gate the compute/hbm split on a single leg."""
        with collector.workload("wl"):
            with collector.measured_region():
                with profile_window("leg.a") as w:
                    w.device(0.1)
                with profile_window("leg.b") as w:
                    w.device(0.2)
                with profile_window("leg.c") as w:
                    w.dispatch(0.1)        # no device mark: not a leg
        attr = collector.workload_attribution("wl")
        assert attr["device_scopes"] == ["leg.a", "leg.b"]

    def test_discard_workload_drops_aborted_attempt(self, collector):
        """The bench retry path: a failed attempt's marks, wall and HBM
        snapshots must not double into the retry's attribution."""
        with collector.workload("wl"):
            with collector.measured_region():
                with profile_window("s") as w:
                    w.dispatch(5.0)       # the aborted attempt
            collector.hbm_snapshot("boundary")
        # an aborted xprof capture's per-scope budget is given back too
        with collector._lock:
            collector._captures.append(
                {"workload": "wl", "scope": "s", "dir": "/x",
                 "window_wall_s": 0.1, "parsed": None})
            collector._capture_counts["s"] = 1
        collector.discard_workload("wl")
        assert collector.workload_attribution("wl") is None
        assert collector.summary()["hbm"] == []
        assert collector.summary()["captures"] == []
        assert collector._capture_counts.get("s", 0) == 0
        with collector.workload("wl"):
            with collector.measured_region():
                with profile_window("s") as w:
                    w.dispatch(0.25)      # the retry
        attr = collector.workload_attribution("wl")
        assert attr["dispatch_s"] == pytest.approx(0.25)

    def test_export_round_trips(self, collector, tmp_path):
        with collector.workload("wl"):
            with collector.measured_region():
                with profile_window("s") as w:
                    w.dispatch(0.1)
            collector.hbm_snapshot("boundary")
        p = str(tmp_path / "profile.json")
        collector.export(p)
        doc = json.load(open(p))
        assert doc["format"] == p2.PROFILE_FORMAT
        assert "wl" in doc["workloads"]
        assert doc["hbm"] and doc["hbm"][0]["scope"] == "boundary"

    def test_off_flag_is_null_window(self, monkeypatch):
        monkeypatch.delenv("ALINK_TPU_PROFILE", raising=False)
        w = profile_window("s")
        assert w.on is False
        with w as ww:
            ww.dispatch(1.0)       # discards
        assert p2.hbm_snapshot("x") is None

    def test_mark_rejects_unknown_bucket(self, collector):
        with pytest.raises(ValueError):
            p2.mark("s", "frobnicate", 1.0)


class TestMeasuredBound:
    def _attr(self, **kw):
        base = {"dispatch_s": 0.0, "transfer_s": 0.0, "device_s": 0.0,
                "collective_s": 0.0, "host_s": 0.0,
                "measured_wall_s": 1.0}
        base.update(kw)
        return base

    def test_dispatch_dominant_is_latency(self):
        b, fr = measured_bound(self._attr(dispatch_s=0.8, device_s=0.2))
        assert b == "latency" and fr["dispatch"] == pytest.approx(0.8)

    def test_transfer_dominant_is_link(self):
        assert measured_bound(self._attr(transfer_s=0.9))[0] == "link"

    def test_host_dominant_is_host(self):
        assert measured_bound(self._attr(host_s=0.9))[0] == "host"

    def test_collective_dominant(self):
        assert measured_bound(
            self._attr(collective_s=0.9))[0] == "collective"

    def test_device_without_model_is_device(self):
        assert measured_bound(self._attr(device_s=0.9))[0] == "device"

    def test_device_with_model_splits_compute_vs_hbm(self):
        attr = self._attr(device_s=0.9)
        # compute-heavy: huge flops per sample, tiny bytes
        b, _ = measured_bound(attr, flops_per_sample=1e9,
                              bytes_per_sample=1.0,
                              samples_per_sec_per_chip=1e6,
                              peak_tflops=197.0, peak_hbm_gbps=819.0)
        assert b == "compute"
        # byte-heavy: the reverse
        b, _ = measured_bound(attr, flops_per_sample=1.0,
                              bytes_per_sample=1e6,
                              samples_per_sec_per_chip=1e6,
                              peak_tflops=197.0, peak_hbm_gbps=819.0)
        assert b == "hbm"


def _write_chrome_trace(path, events, pid_names):
    doc = {"traceEvents": (
        [{"ph": "M", "name": "process_name", "pid": pid,
          "args": {"name": nm}} for pid, nm in pid_names.items()]
        + events)}
    with gzip.open(path, "wt") as f:
        json.dump(doc, f)


class TestXprofParser:
    def test_device_lane_attribution(self, tmp_path):
        p = str(tmp_path / "x.trace.json.gz")
        _write_chrome_trace(p, [
            {"ph": "X", "pid": 2, "tid": 1, "name": "fusion.42",
             "ts": 0.0, "dur": 2_000_000.0},
            {"ph": "X", "pid": 2, "tid": 1, "name": "all-reduce.1",
             "ts": 2_000_000.0, "dur": 500_000.0},
            {"ph": "X", "pid": 2, "tid": 1, "name": "copy-start.3",
             "ts": 2_500_000.0, "dur": 250_000.0},
            # host lane noise that must be ignored
            {"ph": "X", "pid": 9, "tid": 7, "name": "python_call",
             "ts": 0.0, "dur": 9_000_000.0},
        ], {2: "/device:TPU:0", 9: "/host:CPU"})
        got = parse_xprof_trace(p)
        assert got["device_s"] == pytest.approx(2.0)
        assert got["collective_s"] == pytest.approx(0.5)
        assert got["transfer_s"] == pytest.approx(0.25)
        assert got["busy_s"] == pytest.approx(2.75)
        assert got["events"] == 3
        assert got["lanes"] == ["/device:TPU:0"]

    def test_host_only_trace_returns_none(self, tmp_path):
        """CPU rigs (no TensorBoard device plugin lanes) must fall back
        to the timing harness — the parser says so by returning None."""
        p = str(tmp_path / "h.trace.json.gz")
        _write_chrome_trace(p, [
            {"ph": "X", "pid": 9, "tid": 7, "name": "python_call",
             "ts": 0.0, "dur": 100.0}], {9: "/host:CPU"})
        assert parse_xprof_trace(p) is None

    def test_directory_search_and_malformed_tolerance(self, tmp_path):
        d = tmp_path / "plugins" / "profile" / "2026_01_01"
        d.mkdir(parents=True)
        with open(d / "broken.trace.json", "w") as f:
            f.write("{not json")
        _write_chrome_trace(str(d / "ok.trace.json.gz"), [
            {"ph": "X", "pid": 2, "tid": 1, "name": "fusion.1",
             "ts": 0.0, "dur": 1_000_000.0}], {2: "/device:TPU:0"})
        got = parse_xprof_trace(str(tmp_path))
        assert got and got["device_s"] == pytest.approx(1.0)

    def test_missing_path_returns_none(self, tmp_path):
        assert parse_xprof_trace(str(tmp_path / "nope")) is None


class TestXprofCapture:
    def test_capture_bounded_one_per_scope(self, collector, monkeypatch,
                                           tmp_path):
        monkeypatch.setenv("ALINK_TPU_PROFILE_DIR", str(tmp_path))
        monkeypatch.setenv("ALINK_TPU_PROFILE_XPROF", "1")
        import jax
        import jax.numpy as jnp
        for _ in range(2):
            with profile_window("cap.scope", capture=True):
                jax.block_until_ready(jnp.ones(8) + 1)
        caps = collector.summary()["captures"]
        assert len(caps) == 1                     # per-scope cap honored
        capdir = caps[0]["dir"]
        assert os.path.isdir(capdir)
        files = [f for _, _, fs in os.walk(capdir) for f in fs]
        assert files, "profiler capture produced no files"
        # this rig's trace is host-lane-only -> harness fallback
        attrs = collector.workload_attribution(None)
        assert caps[0]["parsed"] is None or "busy_s" in caps[0]["parsed"]
        assert attrs is None or "source" in attrs

    def test_capture_without_dir_is_skipped(self, collector, monkeypatch):
        monkeypatch.setenv("ALINK_TPU_PROFILE_XPROF", "1")
        with profile_window("nodir.scope", capture=True):
            pass
        assert collector.summary()["captures"] == []

    def test_bench_warmup_window_never_spends_the_budget(
            self, collector, monkeypatch, tmp_path):
        """Under a named workload (the bench), only MEASURED windows
        capture — the first window of a scope is the warmup/compile
        call, and a trace of compile time is not steady state."""
        monkeypatch.setenv("ALINK_TPU_PROFILE_DIR", str(tmp_path))
        monkeypatch.setenv("ALINK_TPU_PROFILE_XPROF", "1")
        import jax
        import jax.numpy as jnp
        with collector.workload("wl"):
            with profile_window("warm.scope", capture=True):   # warmup
                jax.block_until_ready(jnp.ones(4) + 1)
            assert collector.summary()["captures"] == []
            with collector.measured_region():
                with profile_window("warm.scope", capture=True):
                    jax.block_until_ready(jnp.ones(4) + 1)
        caps = collector.summary()["captures"]
        assert len(caps) == 1 and caps[0]["workload"] == "wl"


class TestHbmAndDonation:
    def test_live_bytes_counts_nondeleted(self):
        import jax
        x = jax.device_put(np.zeros(1024, np.float32))
        jax.block_until_ready(x)
        assert p2.live_hbm_bytes() >= x.nbytes

    def test_hbm_snapshot_records_and_gauges(self, collector, monkeypatch):
        from alink_tpu.common.metrics import MetricsRegistry, set_registry
        reg = MetricsRegistry()
        prev = set_registry(reg)
        try:
            with collector.workload("wl"):
                got = collector.hbm_snapshot("chunk.boundary")
            assert got is not None and got >= 0
            assert reg.value("alink_hbm_live_bytes",
                             {"scope": "chunk.boundary"}) == got
        finally:
            set_registry(prev)

    def test_donation_probe_verifies_halving(self, collector):
        """THE measured PR-5 claim: a donated carry update holds ~half
        the resident state of the undonated twin while the pre-step
        buffer is still referenced."""
        got = donation_probe(state_bytes=1 << 20, steps=2)
        assert got["verified"] is True
        assert got["ratio"] <= 0.75
        assert got["donated_peak_bytes"] < got["undonated_peak_bytes"]
        # recorded on the collector for the profile artifact
        assert collector.summary()["donation"]["verified"] is True


class TestEngineIntegration:
    def test_exec_attribution_and_memo_contract(self, collector):
        """A profiled exec records dispatch/device marks and an HBM
        snapshot — and the ComQueueResult read-only memo contract
        survives: fetched arrays stay read-only and identity-stable."""
        with collector.workload("engine_wl"):
            with collector.measured_region():
                res = _queue(_env(), key=("p2eng", time.time())).exec()
            a = res.shards("acc")
            collector.hbm_snapshot("after.fetch")
            b = res.shards("acc")
        assert a is b                       # memoized, not re-fetched
        assert not a.flags.writeable
        with pytest.raises(ValueError):
            a[0] = 0.0
        attr = collector.workload_attribution("engine_wl")
        assert attr["dispatch_s"] > 0
        assert attr["device_s"] >= 0
        hbm = collector.summary()["hbm"]
        scopes = {r["scope"] for r in hbm}
        assert "comqueue.exec" in scopes

    def test_chunked_exec_records_chunk_marks(self, collector, tmp_path):
        with collector.workload("ckpt_wl"):
            with collector.measured_region():
                q = _queue(_env(), max_iter=4,
                           key=("p2chunk", time.time()))
                q.set_checkpoint(str(tmp_path / "ck"), every=2)
                q.exec()
        marks = collector.summary()["marks"]
        chunk = [m for m in marks if m["scope"] == "comqueue.chunk"
                 and m["measured"]]
        assert any(m["bucket"] == "dispatch" for m in chunk)
        assert any(m["bucket"] == "device" for m in chunk)
        scopes = {r["scope"] for r in collector.summary()["hbm"]}
        assert "comqueue.chunk" in scopes

    def test_results_identical_with_profiling(self, monkeypatch):
        """Profiling must never perturb computed values."""
        monkeypatch.delenv("ALINK_TPU_PROFILE", raising=False)
        key = ("p2val", time.time())
        r_off = _queue(_env(), key=key).exec().get("acc").copy()
        monkeypatch.setenv("ALINK_TPU_PROFILE", "1")
        col = ProfileCollector()
        prev = set_profiler(col)
        try:
            r_on = _queue(_env(), key=key).exec().get("acc").copy()
        finally:
            set_profiler(prev)
        np.testing.assert_array_equal(r_off, r_on)


class TestFlagsRegistered:
    def test_profile_flags_declared(self):
        from alink_tpu.common.flags import FLAGS
        for name in ("ALINK_TPU_PROFILE", "ALINK_TPU_PROFILE_DIR",
                     "ALINK_TPU_PROFILE_XPROF"):
            f = FLAGS.get(name)
            assert f is not None, name
            assert f.key_neutral, f"{name} must justify key-neutrality"
