"""Objective functions for linear-model training.

Re-design of the reference optimization objectives
(common/optim/objfunc/OptimObjFunc.java:60-80 ``calcGradient/updateGradient``;
common/linear/UnaryLossObjFunc.java; the 11 per-loss classes under
common/linear/unarylossfunc/ — LogLoss, Hinge, SmoothHinge, Square, Huber,
Exponential, Perceptron, Svr, ZeroOne).

TPU-first shape: objectives are pure jax functions over a **shard** of
training data held as device arrays — dense ``{"X"}`` or padded-COO sparse
``{"idx","val"}`` plus ``{"y","w"}`` — returning unnormalized sums
(grad, loss, weight). Cross-worker normalization happens after an
``AllReduce``, mirroring the reference's gradAllReduce/lossAllReduce stages.
Per-sample Java loops become one fused matmul/gather per shard (MXU).
Sample weights double as the padding mask (padded rows have w == 0).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# unary losses: loss(eta, y) and d loss / d eta, with y in {-1, +1} for
# classification losses and real y for regression losses.
# ---------------------------------------------------------------------------

class UnaryLossFunc:
    name = "base"

    def loss(self, eta, y):  # pragma: no cover - interface
        raise NotImplementedError

    def derivative(self, eta, y):  # pragma: no cover - interface
        raise NotImplementedError

    def second_derivative(self, eta, y):
        raise NotImplementedError(f"{self.name} has no curvature (Newton unsupported)")


class LogLossFunc(UnaryLossFunc):
    """logistic loss (reference unarylossfunc/LogLossFunc.java)."""
    name = "log"

    def loss(self, eta, y):
        # log(1 + exp(-y*eta)), stable
        m = -y * eta
        return jnp.logaddexp(0.0, m)

    def derivative(self, eta, y):
        return -y * jax.nn.sigmoid(-y * eta)

    def second_derivative(self, eta, y):
        p = jax.nn.sigmoid(y * eta)
        return p * (1.0 - p)


class HingeLossFunc(UnaryLossFunc):
    name = "hinge"

    def loss(self, eta, y):
        return jnp.maximum(0.0, 1.0 - y * eta)

    def derivative(self, eta, y):
        return jnp.where(y * eta < 1.0, -y, 0.0)


class SmoothHingeLossFunc(UnaryLossFunc):
    """quadratically-smoothed hinge (reference SmoothHingeLossFunc.java)."""
    name = "smooth_hinge"

    def __init__(self, gamma: float = 1.0):
        self.gamma = gamma

    def loss(self, eta, y):
        z = y * eta
        g = self.gamma
        return jnp.where(z >= 1.0, 0.0,
                         jnp.where(z <= 1.0 - g, 1.0 - z - g / 2,
                                   (1.0 - z) ** 2 / (2 * g)))

    def derivative(self, eta, y):
        z = y * eta
        g = self.gamma
        return jnp.where(z >= 1.0, 0.0,
                         jnp.where(z <= 1.0 - g, -y, -y * (1.0 - z) / g))


class SquareLossFunc(UnaryLossFunc):
    name = "square"

    def loss(self, eta, y):
        return 0.5 * (eta - y) ** 2

    def derivative(self, eta, y):
        return eta - y

    def second_derivative(self, eta, y):
        return jnp.ones_like(eta)


class SvrLossFunc(UnaryLossFunc):
    """epsilon-insensitive (reference SvrLossFunc.java)."""
    name = "svr"

    def __init__(self, epsilon: float = 0.1):
        self.epsilon = epsilon

    def loss(self, eta, y):
        return jnp.maximum(0.0, jnp.abs(y - eta) - self.epsilon)

    def derivative(self, eta, y):
        r = eta - y
        return jnp.where(jnp.abs(r) <= self.epsilon, 0.0, jnp.sign(r))


class HuberLossFunc(UnaryLossFunc):
    name = "huber"

    def __init__(self, delta: float = 1.0):
        self.delta = delta

    def loss(self, eta, y):
        r = jnp.abs(eta - y)
        d = self.delta
        return jnp.where(r <= d, 0.5 * r ** 2, d * (r - 0.5 * d))

    def derivative(self, eta, y):
        r = eta - y
        d = self.delta
        return jnp.clip(r, -d, d)


class ExponentialLossFunc(UnaryLossFunc):
    name = "exponential"

    def loss(self, eta, y):
        return jnp.exp(-y * eta)

    def derivative(self, eta, y):
        return -y * jnp.exp(-y * eta)


class PerceptronLossFunc(UnaryLossFunc):
    name = "perceptron"

    def loss(self, eta, y):
        return jnp.maximum(0.0, -y * eta)

    def derivative(self, eta, y):
        return jnp.where(y * eta < 0.0, -y, 0.0)


class ZeroOneLossFunc(UnaryLossFunc):
    name = "zero_one"

    def loss(self, eta, y):
        return (jnp.sign(eta) != y).astype(eta.dtype)

    def derivative(self, eta, y):
        return jnp.zeros_like(eta)


LOSS_REGISTRY = {
    "log": LogLossFunc, "hinge": HingeLossFunc, "smooth_hinge": SmoothHingeLossFunc,
    "square": SquareLossFunc, "svr": SvrLossFunc, "huber": HuberLossFunc,
    "exponential": ExponentialLossFunc, "perceptron": PerceptronLossFunc,
    "zero_one": ZeroOneLossFunc,
}


# ---------------------------------------------------------------------------
# design-matrix ops over a data shard
# ---------------------------------------------------------------------------

def _fb_parts(data: Dict):
    """Precomputed one-hot factors, when the trainer's init superstep
    materialized them into the shard dict (fb_onehot_parts)."""
    if "fb_A" in data:
        return data["fb_A"], data["fb_B"]
    return None


def matvec(data: Dict, coef, fb_meta=None):
    """margins = X @ coef for dense, padded-COO, or field-blocked shard.

    Field-blocked shards ({"fb_idx"}) route to the factored-one-hot MXU
    kernel (ops/fieldblock.py) instead of XLA's serialized random gather.
    """
    if "X" in data:
        return data["X"] @ coef
    if "fb_idx" in data:
        if fb_meta is None:
            raise ValueError("shard has 'fb_idx' but no FieldBlockMeta was "
                             "provided (pass fb_meta= to the objective)")
        from ....ops.fieldblock import fb_matvec
        return fb_matvec(data["fb_idx"], coef, fb_meta, val=data.get("fb_val"),
                         parts=_fb_parts(data))
    return (data["val"] * coef[data["idx"]]).sum(-1)


def rmatvec(data: Dict, c, dim: int, fb_meta=None):
    """X^T @ c — gradient accumulation.

    Dense: one matmul. Field-blocked: scatter-free factored one-hot
    (ops/fieldblock.py). Padded-COO: XLA scatter-add (slow on TPU — the
    general-sparsity fallback)."""
    if "X" in data:
        return data["X"].T @ c
    if "fb_idx" in data:
        if fb_meta is None:
            raise ValueError("shard has 'fb_idx' but no FieldBlockMeta was "
                             "provided (pass fb_meta= to the objective)")
        from ....ops.fieldblock import fb_rmatvec
        return fb_rmatvec(data["fb_idx"], c, fb_meta, val=data.get("fb_val"),
                          parts=_fb_parts(data))
    contrib = data["val"] * c[:, None]
    return jnp.zeros(dim, contrib.dtype).at[data["idx"].reshape(-1)].add(
        contrib.reshape(-1))


def densify_shard(data: Dict, dim: int, fb_meta=None):
    """(n, dim) dense design matrix from any shard layout.

    Only for algorithms whose memory is already O(dim^2) — Newton's Hessian
    (reference common/optim/Newton.java runs on any vector input because its
    Hessian is a dense dim x dim matrix regardless) — where the O(n*dim)
    scatter-densify is not the dominant cost. Hot gradient paths must keep
    using matvec/rmatvec, which never densify.
    """
    if "X" in data:
        return data["X"]
    if "fb_idx" in data:
        if fb_meta is None:
            raise ValueError("shard has 'fb_idx' but no FieldBlockMeta was "
                             "provided (pass fb_meta= to the objective)")
        offs = jnp.arange(fb_meta.num_fields, dtype=data["fb_idx"].dtype) \
            * fb_meta.field_size
        idx = data["fb_idx"] + offs[None, :]
        val = data.get("fb_val")
        if val is None:
            val = jnp.ones(idx.shape, jnp.float32)
    else:
        idx, val = data["idx"], data["val"]
    n = idx.shape[0]
    # padding entries carry val == 0, so scatter-add at their (0-)index is a no-op
    return jnp.zeros((n, dim), val.dtype).at[
        jnp.arange(n)[:, None], idx].add(val)


class OptimObjFunc:
    """Base objective: per-shard grad/loss/hessian + global regularization."""

    def __init__(self, dim: int, l1: float = 0.0, l2: float = 0.0,
                 reg_free_head: int = 0):
        self.dim = int(dim)
        self.l1 = float(l1)
        self.l2 = float(l2)
        # first `reg_free_head` coefficients (the intercept) are unregularized
        self.reg_free_head = int(reg_free_head)

    def _reg_mask(self, coef):
        if self.reg_free_head == 0:
            return jnp.ones_like(coef)
        return jnp.concatenate([jnp.zeros(self.reg_free_head, coef.dtype),
                                jnp.ones(self.dim - self.reg_free_head, coef.dtype)])

    def regular_loss(self, coef):
        m = self._reg_mask(coef)
        return (0.5 * self.l2 * ((coef * m) ** 2).sum()
                + self.l1 * jnp.abs(coef * m).sum())

    def l2_grad(self, coef):
        return self.l2 * coef * self._reg_mask(coef)

    # interface ----------------------------------------------------------
    def calc_grad_shard(self, data, coef):
        """-> (grad_sum, loss_sum, weight_sum) — unnormalized shard sums."""
        raise NotImplementedError

    def calc_grad_eta_shard(self, data, coef):
        """-> (grad, loss, wsum, eta); eta (per-shard margins at coef) may be
        passed back to line_losses_shard to skip recomputing the matvec."""
        grad, loss, wsum = self.calc_grad_shard(data, coef)
        return grad, loss, wsum, None

    def line_losses_shard(self, data, coef, direction, steps, eta0=None):
        """losses at coef - steps[j]*direction -> (num_steps,) shard sums."""
        raise NotImplementedError

    def hessian_shard(self, data, coef):
        raise NotImplementedError


class UnaryLossObjFunc(OptimObjFunc):
    """sum_i w_i * loss(x_i . coef, y_i) (reference common/linear/UnaryLossObjFunc.java).

    ``fb_meta`` (ops.fieldblock.FieldBlockMeta) enables the field-blocked
    fast path when the shard carries ``fb_idx``.
    """

    def __init__(self, unary_loss: UnaryLossFunc, dim: int, l1=0.0, l2=0.0,
                 reg_free_head: int = 0, fb_meta=None):
        super().__init__(dim, l1, l2, reg_free_head)
        self.unary_loss = unary_loss
        if fb_meta is not None and fb_meta.dim != self.dim:
            raise ValueError(f"fb_meta.dim {fb_meta.dim} != objective dim "
                             f"{self.dim} (dim must be num_fields*field_size)")
        self.fb_meta = fb_meta

    def calc_grad_shard(self, data, coef):
        grad, loss, wsum, _ = self.calc_grad_eta_shard(data, coef)
        return grad, loss, wsum

    def calc_grad_eta_shard(self, data, coef):
        """(grad, loss, wsum, eta) — eta is reusable by the same-superstep
        line search (margins at the unmoved coef), saving one matvec pass."""
        eta = matvec(data, coef, self.fb_meta)
        y, w = data["y"], data["w"]
        loss = (w * self.unary_loss.loss(eta, y)).sum()
        c = w * self.unary_loss.derivative(eta, y)
        grad = rmatvec(data, c, self.dim, self.fb_meta)
        return grad, loss, w.sum(), eta

    def line_losses_shard(self, data, coef, direction, steps, eta0=None):
        if eta0 is None:
            eta0 = matvec(data, coef, self.fb_meta)
        etad = matvec(data, direction, self.fb_meta)
        y, w = data["y"], data["w"]

        def one(s):
            return (w * self.unary_loss.loss(eta0 - s * etad, y)).sum()

        return jax.vmap(one)(steps)

    def hessian_shard(self, data, coef):
        grad, loss, wsum, eta = self.calc_grad_eta_shard(data, coef)
        y, w = data["y"], data["w"]
        h = w * self.unary_loss.second_derivative(eta, y)
        Xd = densify_shard(data, self.dim, self.fb_meta)
        H = (Xd * h[:, None]).T @ Xd
        return H, grad, loss, wsum


class SoftmaxObjFunc(OptimObjFunc):
    """Multinomial logistic objective (reference common/linear/SoftmaxObjFunc.java).

    coef is the flattened (k-1, d) matrix — class k-1 is the pivot with zero
    logits, matching the reference's k-1 parameterization. ``data["y"]``
    holds integer class indices.
    """

    def __init__(self, k: int, d: int, l1=0.0, l2=0.0, reg_free_cols: int = 0):
        super().__init__((k - 1) * d, l1, l2, reg_free_head=0)
        self.k = int(k)
        self.d = int(d)
        self.reg_free_cols = reg_free_cols  # leading feature columns w/o reg (intercept)

    def _reg_mask(self, coef):
        m = jnp.ones((self.k - 1, self.d), coef.dtype)
        if self.reg_free_cols:
            m = m.at[:, :self.reg_free_cols].set(0.0)
        return m.reshape(-1)

    def _logits(self, data, W):
        if "X" in data:
            z = data["X"] @ W.T  # (n, k-1)
        else:
            gathered = W.T[data["idx"]]           # (n, nnz, k-1)
            z = (gathered * data["val"][..., None]).sum(1)
        return jnp.concatenate([z, jnp.zeros((z.shape[0], 1), z.dtype)], axis=1)

    def _grad_loss_from_logits(self, data, logits):
        """(grad, loss, wsum, softmax probs) at already-computed logits —
        shared by the gradient and Newton paths so each Newton step runs
        the design-matrix product once."""
        y, w = data["y"].astype(jnp.int32), data["w"]
        lse = jax.nn.logsumexp(logits, axis=1)
        loss = (w * (lse - jnp.take_along_axis(logits, y[:, None], 1)[:, 0])).sum()
        p = jax.nn.softmax(logits, axis=1)
        delta = (p - jax.nn.one_hot(y, self.k, dtype=p.dtype)) * w[:, None]  # (n,k)
        delta = delta[:, :self.k - 1]  # drop pivot class
        if "X" in data:
            grad = (delta.T @ data["X"]).reshape(-1)
        else:
            contrib = delta[:, None, :] * data["val"][:, :, None]  # (n, nnz, k-1)
            flat_idx = data["idx"].reshape(-1)
            g = jnp.zeros((self.d, self.k - 1), contrib.dtype)
            g = g.at[flat_idx].add(contrib.reshape(-1, self.k - 1))
            grad = g.T.reshape(-1)
        return grad, loss, w.sum(), p

    def calc_grad_shard(self, data, coef):
        W = coef.reshape(self.k - 1, self.d)
        grad, loss, wsum, _ = self._grad_loss_from_logits(
            data, self._logits(data, W))
        return grad, loss, wsum

    def line_losses_shard(self, data, coef, direction, steps, eta0=None):
        W = coef.reshape(self.k - 1, self.d)
        D = direction.reshape(self.k - 1, self.d)
        y, w = data["y"].astype(jnp.int32), data["w"]
        z0 = self._logits(data, W)
        zd = self._logits(data, D)

        def one(s):
            z = z0 - s * zd
            lse = jax.nn.logsumexp(z, axis=1)
            return (w * (lse - jnp.take_along_axis(z, y[:, None], 1)[:, 0])).sum()

        return jax.vmap(one)(steps)

    def hessian_shard(self, data, coef):
        """Full (k-1)d x (k-1)d Hessian (reference SoftmaxObjFunc.java
        calcHessian): block (a,b) is sum_i w_i (p_ia [a==b] - p_ia p_ib)
        x_i x_i^T, laid out to match the flattened (k-1, d) coef.

        Blocks are contracted one (a,b) pair at a time under lax.map so
        peak memory stays O(n*d) — a single three-operand einsum would
        materialize an O(n*d^2) or O(n*(k-1)^2*d) intermediate."""
        W = coef.reshape(self.k - 1, self.d)
        logits = self._logits(data, W)
        grad, loss, wsum, p_full = self._grad_loss_from_logits(data, logits)
        w = data["w"]
        p = p_full[:, :self.k - 1]
        Xd = densify_shard(data, self.d)
        km1 = self.k - 1
        pairs = jnp.stack(jnp.meshgrid(jnp.arange(km1), jnp.arange(km1),
                                       indexing="ij"), -1).reshape(-1, 2)

        def block(pair):
            a, b = pair[0], pair[1]
            same = (a == b).astype(p.dtype)
            s = w * (p[:, a] * same - p[:, a] * p[:, b])
            return Xd.T @ (s[:, None] * Xd)

        blocks = jax.lax.map(block, pairs)         # ((k-1)^2, d, d)
        H = (blocks.reshape(km1, km1, self.d, self.d)
             .transpose(0, 2, 1, 3).reshape(self.dim, self.dim))
        return H, grad, loss, wsum
