"""MTable — the host-side columnar table.

Replaces the reference's Flink ``Table``/``Row`` substrate (operators there
produce Tables; models are Tables of Rows). TPU-first split: strings and
objects live in host numpy columns; only encoded numeric tensors are shipped
to the device (SURVEY §7 "Rows of strings never touch the TPU").

Columns are numpy arrays (numeric dtypes, or dtype=object for strings /
vectors / nested MTables).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from .types import AlinkTypes, TableSchema
from .vector import DenseVector, SparseVector, VectorUtil


class MTable:
    def __init__(self, columns: Union[Dict[str, Any], Sequence[Sequence[Any]], np.ndarray],
                 schema: Union[TableSchema, str, Sequence[str], None] = None):
        if isinstance(schema, str):
            schema = TableSchema.parse(schema)

        if isinstance(columns, dict):
            names = list(columns.keys())
            cols = [_as_column(v) for v in columns.values()]
        else:
            # row-major input: list of rows (tuples) or 2-D ndarray
            if isinstance(columns, np.ndarray) and columns.ndim == 2:
                rows = [tuple(r) for r in columns]
            else:
                rows = [tuple(r) if isinstance(r, (tuple, list, np.ndarray)) else (r,)
                        for r in columns]
            ncol = len(rows[0]) if rows else (len(schema) if schema is not None else 0)
            cols = [_as_column([r[j] for r in rows]) for j in range(ncol)]
            if isinstance(schema, TableSchema):
                names = list(schema.names)
            elif schema is not None:
                names = list(schema)
                schema = None
            else:
                names = [f"col{j}" for j in range(ncol)]

        if isinstance(schema, TableSchema):
            self.schema = schema.copy()
            names = schema.names
        else:
            if schema is not None and not isinstance(schema, TableSchema):
                names = list(schema)
            types = [_infer_type(c) for c in cols]
            self.schema = TableSchema(names, types)

        if len(cols) != len(self.schema):
            raise ValueError(f"{len(cols)} columns vs schema of {len(self.schema)}")
        n = cols[0].shape[0] if cols else 0
        for c in cols:
            if c.shape[0] != n:
                raise ValueError("ragged columns")
        self._cols: Dict[str, np.ndarray] = dict(zip(self.schema.names, cols))

    # ------------------------------------------------------------------
    @property
    def num_rows(self) -> int:
        if not self._cols:
            return 0
        return next(iter(self._cols.values())).shape[0]

    @property
    def col_names(self) -> List[str]:
        return list(self.schema.names)

    @property
    def col_types(self) -> List[str]:
        return list(self.schema.types)

    def col(self, name: str) -> np.ndarray:
        if name not in self._cols:
            raise KeyError(f"column '{name}' not in {self.col_names}")
        return self._cols[name]

    def __getitem__(self, name: str) -> np.ndarray:
        return self.col(name)

    def __len__(self):
        return self.num_rows

    def numeric_block(self, names: Sequence[str], dtype=np.float64) -> np.ndarray:
        """Stack numeric columns into an (n, k) array — the device-encode boundary."""
        return np.stack([np.asarray(self._cols[n], dtype=dtype) for n in names], axis=1) \
            if names else np.zeros((self.num_rows, 0), dtype)

    def rows(self) -> Iterable[Tuple]:
        cols = [self._cols[n] for n in self.schema.names]
        for i in range(self.num_rows):
            yield tuple(c[i] for c in cols)

    def row(self, i: int) -> Tuple:
        return tuple(self._cols[n][i] for n in self.schema.names)

    def to_rows(self) -> List[Tuple]:
        return list(self.rows())

    # -- relational ops (back the SQL operator family) -------------------
    def select(self, names: Union[str, Sequence[str]]) -> "MTable":
        if isinstance(names, str):
            names = [n.strip() for n in names.split(",")]
        sub = TableSchema(names, [self.schema.type_of(n) for n in names])
        return MTable({n: self._cols[n] for n in names}, sub)

    def take_rows(self, idx) -> "MTable":
        idx = np.asarray(idx)
        if idx.dtype != bool:
            idx = idx.astype(np.intp)
        return MTable({n: c[idx] for n, c in self._cols.items()}, self.schema)

    def first_n(self, n: int) -> "MTable":
        return self.take_rows(np.arange(min(n, self.num_rows)))

    def filter_mask(self, mask: np.ndarray) -> "MTable":
        return self.take_rows(np.nonzero(np.asarray(mask, dtype=bool))[0])

    def add_column(self, name: str, values, type_: Optional[str] = None) -> "MTable":
        col = _as_column(values)
        cols = dict(self._cols)
        names, types = list(self.schema.names), list(self.schema.types)
        if name in cols:
            i = names.index(name)
            types[i] = type_ or _infer_type(col)
        else:
            names.append(name)
            types.append(type_ or _infer_type(col))
        cols[name] = col
        return MTable(cols, TableSchema(names, types))

    def drop_columns(self, names: Sequence[str]) -> "MTable":
        keep = [n for n in self.schema.names if n not in set(names)]
        return self.select(keep)

    def rename(self, mapping_or_names) -> "MTable":
        if isinstance(mapping_or_names, dict):
            names = [mapping_or_names.get(n, n) for n in self.schema.names]
        else:
            names = list(mapping_or_names)
        return MTable({new: c for new, c in zip(names, (self._cols[o] for o in self.schema.names))},
                      TableSchema(names, list(self.schema.types)))

    def concat_rows(self, other: "MTable") -> "MTable":
        if other.col_names != self.col_names:
            other = other.select(self.col_names)
        return MTable({n: _concat(self._cols[n], other._cols[n]) for n in self.schema.names},
                      self.schema)

    def order_by(self, name: str, ascending: bool = True, limit: Optional[int] = None) -> "MTable":
        key = self._cols[name]
        try:
            order = np.argsort(key, kind="stable")
        except TypeError:
            order = np.argsort(np.asarray([str(v) for v in key]), kind="stable")
        if not ascending:
            order = order[::-1]
        if limit is not None:
            order = order[:limit]
        return self.take_rows(order)

    def distinct(self) -> "MTable":
        seen, keep = set(), []
        for i, r in enumerate(self.rows()):
            k = tuple(_hashable(v) for v in r)
            if k not in seen:
                seen.add(k)
                keep.append(i)
        return self.take_rows(keep)

    def group_indices(self, by: Sequence[str]) -> Dict[Tuple, np.ndarray]:
        keys: Dict[Tuple, List[int]] = {}
        cols = [self._cols[n] for n in by]
        for i in range(self.num_rows):
            k = tuple(_hashable(c[i]) for c in cols)
            keys.setdefault(k, []).append(i)
        return {k: np.asarray(v) for k, v in keys.items()}

    # ------------------------------------------------------------------
    def clone(self) -> "MTable":
        return MTable({n: c.copy() for n, c in self._cols.items()}, self.schema)

    def __repr__(self):
        return f"MTable[{self.num_rows} rows]({self.schema.to_spec()})"

    def to_display_string(self, max_rows: int = 20) -> str:
        lines = ["\t".join(self.schema.names)]
        for i, r in enumerate(self.rows()):
            if i >= max_rows:
                lines.append(f"... ({self.num_rows} rows)")
                break
            lines.append("\t".join(_cell(v) for v in r))
        return "\n".join(lines)

    # -- (de)serialization ------------------------------------------------
    def to_json_rows(self) -> dict:
        def enc(v, t):
            if AlinkTypes.is_vector(t) or isinstance(v, (DenseVector, SparseVector)):
                return VectorUtil.to_string(VectorUtil.parse(v))
            if isinstance(v, (np.generic,)):
                return v.item()
            if isinstance(v, MTable):
                return v.to_json_rows()
            return None if _is_null(v) else v
        return {
            "schema": self.schema.to_spec(),
            "rows": [[enc(v, t) for v, t in zip(r, self.schema.types)] for r in self.rows()],
        }

    @staticmethod
    def from_json_rows(obj: dict) -> "MTable":
        schema = TableSchema.parse(obj["schema"])
        rows = []
        for r in obj["rows"]:
            out = []
            for v, t in zip(r, schema.types):
                if v is not None and AlinkTypes.is_vector(t):
                    v = VectorUtil.parse(v)
                elif v is not None and t == AlinkTypes.M_TABLE:
                    v = MTable.from_json_rows(v)
                out.append(v)
            rows.append(tuple(out))
        return MTable(rows, schema)


def _as_column(v) -> np.ndarray:
    if getattr(v, "__mtable_column__", False):
        return v  # columnar column classes duck-type the ndarray surface
    if isinstance(v, np.ndarray) and v.ndim == 1:
        return v
    v = list(v)
    if v and isinstance(v[0], (DenseVector, SparseVector, MTable)):
        out = np.empty(len(v), dtype=object)
        out[:] = v
        return out
    arr = np.asarray(v)
    if arr.ndim != 1:
        out = np.empty(len(v), dtype=object)
        out[:] = v
        return out
    if arr.dtype.kind in "US":
        out = np.empty(len(v), dtype=object)
        out[:] = [None if x is None else str(x) for x in v]
        return out
    return arr


def _infer_type(col: np.ndarray) -> str:
    if col.dtype != object:
        return AlinkTypes.from_numpy_dtype(col.dtype)
    for v in col:
        if v is None:
            continue
        return AlinkTypes.from_value(v)
    return AlinkTypes.STRING


def _concat(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    if getattr(a, "__mtable_column__", False):
        same = a.concat_same(b)
        if same is not None:
            return same
        a = a.materialize()
    if getattr(b, "__mtable_column__", False):
        b = b.materialize()
    if a.dtype == object or b.dtype == object:
        out = np.empty(a.shape[0] + b.shape[0], dtype=object)
        out[:a.shape[0]] = a
        out[a.shape[0]:] = b
        return out
    return np.concatenate([a, b])


def _hashable(v):
    if isinstance(v, (DenseVector, SparseVector)):
        return VectorUtil.to_string(v)
    if isinstance(v, np.generic):
        return v.item()
    return v


def _is_null(v) -> bool:
    return v is None or (isinstance(v, float) and np.isnan(v))


def _cell(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)
