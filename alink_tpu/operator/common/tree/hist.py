"""Histogram-based tree building — TPU-native core.

Re-design of common/tree/ (36 files, 7,290 LoC) around one device kernel:
level-wise growth of a perfect binary tree over quantile-binned features.

reference mechanism (parallelcart/, SURVEY §2.3):
  ConstructLocalBin      -> per-worker histogram build (scatter-add here)
  AllReduce("gbdtBin")   -> lax.psum inside the stage
  CalBestSplit (sharded) -> full (node,feature,bin) gain tensor + argmax
                            on device (no DistributedInfo range sharding —
                            the MXU/VPU scans all of it at once)
  Split / UpdateTreeData -> node-id descent array update

Trees are dense arrays (perfect binary tree of ``max_depth``): unsplit nodes
store feature = -1 and route everything left, so shapes stay static for XLA.
Generic over a per-sample stat vector (SURVEY §7: "tree structure on host,
bin statistics on device"):
  regression  stats (y, y^2, 1)      variance gain
  classify    stats (onehot(y), 1)   gini gain
  gbdt        stats (g, h, 1)        xgboost-style gain g^2/(h+lambda)
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# host-side quantile binning
# ---------------------------------------------------------------------------

from ....engine.communication import manifest_psum
from ..dataproc.quantile import DEVICE_BINNING_MIN_CELLS as _DEVICE_BINNING_MIN_CELLS


def make_bin_edges(X: np.ndarray, n_bins: int,
                   cat_mask: Optional[np.ndarray] = None,
                   device: Optional[bool] = None, env=None) -> np.ndarray:
    """(F, n_bins-1) per-feature quantile cut points (padded with +inf).

    Categorical features (``cat_mask[f]`` True; values must be integer
    category codes) get identity edges 0.5, 1.5, ... so every category is
    its own bin — no quantile artifacts (reference
    seriestree/CategoricalSplitter.java treats categories as unordered).

    ``device=None`` auto-selects the distributed histogram-quantile pass
    (dataproc/quantile.py, the SortUtils.pSort analogue) once n*F is large
    enough that per-column host ``np.quantile`` would dominate; True/False
    force it.
    """
    n, F = X.shape
    edges = np.full((F, n_bins - 1), np.inf)
    if device is None:
        device = n * F >= _DEVICE_BINNING_MIN_CELLS
    cont = ([f for f in range(F) if not cat_mask[f]]
            if cat_mask is not None else list(range(F)))
    probs = np.linspace(0, 1, n_bins + 1)[1:-1]
    if device and cont:
        from ..dataproc.quantile import distributed_quantiles
        qs_all = distributed_quantiles(
            np.ascontiguousarray(X[:, cont]), probs, env=env)
    for pos, f in enumerate(cont):
        if device:
            qs = qs_all[pos]
        else:
            v = X[:, f]
            v = v[~np.isnan(v)]   # match the device path's per-column NaN
            qs = np.quantile(v, probs) if v.size else np.array([])
        uq = np.unique(qs)
        uq = uq[np.isfinite(uq)]
        edges[f, :len(uq)] = uq
    if cat_mask is not None:
        for f in range(F):
            if cat_mask[f]:
                arity = min(int(X[:, f].max()) + 1, n_bins)
                edges[f, :max(arity - 1, 0)] = (
                    np.arange(max(arity - 1, 0)) + 0.5)
    return edges


def bin_data(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """(n, F) int32 bin ids in [0, n_bins)."""
    n, F = X.shape
    out = np.empty((n, F), np.int32)
    for f in range(F):
        e = edges[f]
        out[:, f] = np.searchsorted(e[np.isfinite(e)], X[:, f], side="right")
    return out


# ---------------------------------------------------------------------------
# gain / leaf functions over cumulated stat histograms
# ---------------------------------------------------------------------------

def variance_gain(left, right, total, min_leaf):
    """stats = (sum_y, sum_y2, count): SSE reduction."""
    def sse(s):
        return s[..., 1] - s[..., 0] ** 2 / jnp.maximum(s[..., 2], 1e-12)
    ok = (left[..., 2] >= min_leaf) & (right[..., 2] >= min_leaf)
    g = sse(total) - sse(left) - sse(right)
    return jnp.where(ok, g, -jnp.inf)


def variance_leaf(stats):
    return stats[..., 0] / jnp.maximum(stats[..., 2], 1e-12)


def gini_gain(left, right, total, min_leaf):
    """stats = (c_0..c_{k-1}, count): weighted gini impurity decrease."""
    def imp(s):
        cnt = jnp.maximum(s[..., -1], 1e-12)
        return cnt - (s[..., :-1] ** 2).sum(-1) / cnt
    ok = (left[..., -1] >= min_leaf) & (right[..., -1] >= min_leaf)
    g = imp(total) - imp(left) - imp(right)
    return jnp.where(ok, g, -jnp.inf)


def gini_leaf(stats):
    return stats[..., :-1] / jnp.maximum(stats[..., -1:], 1e-12)


def make_xgb_gain(reg_lambda: float):
    def xgb_gain(left, right, total, min_leaf):
        """stats = (g, h, count)."""
        def score(s):
            return s[..., 0] ** 2 / (s[..., 1] + reg_lambda)
        ok = (left[..., 2] >= min_leaf) & (right[..., 2] >= min_leaf)
        g = 0.5 * (score(left) + score(right) - score(total))
        return jnp.where(ok, g, -jnp.inf)
    return xgb_gain


def make_xgb_leaf(reg_lambda: float):
    def xgb_leaf(stats):
        return -stats[..., 0] / (stats[..., 1] + reg_lambda)
    return xgb_leaf


# ---------------------------------------------------------------------------
# the level-wise builder (traceable; runs inside shard_map stages)
# ---------------------------------------------------------------------------

def level_hist(binned, stats, node_id, n_nodes: int, n_bins: int,
               use_onehot: bool, onehot_dtype=None, pre=None):
    """(n_nodes, F, n_bins, m) per-(node,feature,bin) stat sums for one level.

    ``use_onehot`` selects a one-hot MXU einsum instead of scatter-add —
    XLA serializes random scatter on TPU (~2.5x slower than the einsum at
    64 nodes); on CPU the scatter is the fast path.

    ``pre`` (fused path): the level-invariant ``(ohB, s2)`` operands from
    :func:`_fused_hist_precompute`, hoisted out of the level loop — ONE
    implementation of the compensated-split einsum serves both the
    default and the fused kernels (with ``pre=None`` the primitive
    sequence is exactly the pre-fused one, preserving the byte-identical
    flag-off HLO contract)."""
    import jax.numpy as jnp
    n, F = binned.shape
    m = stats.shape[1]
    dt = stats.dtype
    if use_onehot or pre is not None:
        hdt = (pre[0].dtype if pre is not None
               else (onehot_dtype or jnp.bfloat16))
        ohN = (node_id[:, None] == jnp.arange(n_nodes)[None, :]).astype(hdt)
        ohB, s2 = (pre if pre is not None
                   else _fused_hist_precompute(binned, stats, n_bins,
                                               onehot_dtype))
        # contract (node-one-hot x stats) FIRST: the (i, n_nodes, 2m)
        # intermediate is ~KBs/sample, where the old explicit
        # ohB[..., None] * s2 product materialized an (i, F, bins, 2m)
        # tensor (~0.5 GB at adult scale) every level
        h2 = jnp.einsum("in,iM,ifb->nfbM", ohN, s2, ohB,
                        preferred_element_type=jnp.float32)
        return (h2[..., :m] + h2[..., m:]).astype(dt)
    flat_idx = (node_id[:, None] * F + jnp.arange(F)[None, :]) * n_bins + binned
    hist = jnp.zeros((n_nodes * F * n_bins, m), dt)
    hist = hist.at[flat_idx.reshape(-1)].add(jnp.repeat(stats, F, axis=0))
    return hist.reshape(n_nodes, F, n_bins, m)


# ---------------------------------------------------------------------------
# fused histogram kernels (ALINK_TPU_FUSED_HIST) — ISSUE 6 tentpole (b)
# ---------------------------------------------------------------------------
#
# The default per-level formulation rebuilds the bin one-hot AND the
# compensated hi/lo stat split EVERY level even though both are
# level-invariant within one tree, and on non-TPU backends it falls back
# to a scatter-add that materializes an (n*F, m) jnp.repeat of the stats.
# The fused kernel hoists the level-invariant operands out of the level
# loop and reduces each level to ONE batched contraction
# (gradient+hessian+count together, all nodes x features x bins at once):
#
#   "xla"    — precompute ohB (n, F, B) + s2 (n, 2m) once per tree; per
#              level a single einsum "in,iM,ifb->nfbM" (two MXU dots, no
#              giant intermediate) on every backend.
#   "pallas" — a hand-written accumulation kernel: grid over
#              (feature, row-block), each step one-hots the COMBINED
#              (node, bin) id in VMEM and accumulates a (B_blk, Q)^T @
#              (B_blk, m) dot into the output block — exact f32
#              accumulation, no hi/lo split, no HBM one-hot
#              materialization. Gated on backend availability (TPU, or
#              interpret mode for tests); demotes to "xla" with a
#              one-time warning when lowering fails.
#
# The mode is resolved at TRACE time and folded into the engine
# program-cache key by the tree trainers, so toggling recompiles instead
# of serving a stale program. With the flag off, build_tree executes the
# pre-existing statements unchanged — the lowered HLO is byte-identical
# to pre-flag programs (pinned by tests/test_perf_kernels.py) and the
# collective set (one psum per level, after the histogram) is identical
# in every mode.

FUSED_HIST_ENV = "ALINK_TPU_FUSED_HIST"
_PALLAS_WARNED = [False]


def fused_hist_mode() -> str:
    """Resolved fused-histogram mode: "off" (default) | "xla" | "pallas".

    ``ALINK_TPU_FUSED_HIST`` values: 0/off/false -> "off"; "pallas" ->
    the Pallas kernel when the backend can run it (TPU, or any backend
    with ``ALINK_TPU_PALLAS_INTERPRET=1``), else "xla"; anything truthy
    else -> "xla". The raw value parses through the flag registry
    (common/flags.py — which also declares the program-cache-key fold);
    only the backend gating lives here. The RESOLVED mode is what the
    tree trainers fold into their program keys, so the interpret flag
    needs no fold of its own. The availability check is the kernel
    tier's shared one (``kernels/runtime.pallas_available`` — the
    ISSUE 13 dedupe of the contract this kernel pioneered)."""
    from ....common.flags import flag_value
    from ....kernels.runtime import pallas_available
    v = flag_value(FUSED_HIST_ENV)
    if v == "pallas" and not pallas_available():
        return "xla"
    return v


def _fused_hist_precompute(binned, stats, n_bins: int, onehot_dtype=None):
    """The one-hot-path operands of :func:`level_hist` that are
    level-invariant within one tree (the fused kernel builds them once;
    the default kernel calls this per level — ONE implementation).

    Compensated bf16 split of the stats: hi + lo reconstructs f32 to
    ~2^-16 relative, so the bf16 MXU path does not quantize grad/hess
    per element (~0.4%) and near-tie splits agree with the exact CPU
    scatter. One einsum over the stacked (hi|lo) stats downstream,
    halves summed in f32 after."""
    hdt = onehot_dtype or jnp.bfloat16
    ohB = (binned[..., None] == jnp.arange(n_bins)[None, None, :]).astype(hdt)
    s32 = stats.astype(jnp.float32)
    s_hi = s32.astype(hdt)
    s_lo = (s32 - s_hi.astype(jnp.float32)).astype(hdt)
    s2 = jnp.concatenate([s_hi, s_lo], axis=1)               # (n, 2m)
    return ohB, s2


def _pallas_level_hist(binned, stats, node_id, n_nodes: int, n_bins: int):
    """Hand-written histogram accumulation kernel (tentpole (b) Pallas
    path): grid (feature, row-block); each step builds the combined
    (node, bin) one-hot for its rows IN VMEM and accumulates one
    ``(Q, blk) @ (blk, m)`` dot into its feature's output block. Exact
    f32 accumulation (no bf16 quantization, no hi/lo split); the only
    HBM traffic is the binned rows, the stats, and the output —
    the one-hot never materializes outside VMEM. Falls back to the XLA
    fused formulation (one-time warning) if lowering/tracing fails."""
    from jax.experimental import pallas as pl

    n, F = binned.shape
    m = stats.shape[1]
    Q = n_nodes * n_bins
    blk = min(512, max(8, n))
    npad = -(-n // blk) * blk
    if npad != n:                      # zero-stat rows are inert
        pz = npad - n
        binned = jnp.concatenate([binned, jnp.zeros((pz, F), binned.dtype)])
        node_id = jnp.concatenate([node_id, jnp.zeros((pz,), node_id.dtype)])
        stats = jnp.concatenate(
            [stats, jnp.zeros((pz, m), stats.dtype)])
    s32 = stats.astype(jnp.float32)
    nid2 = node_id[:, None].astype(jnp.int32)               # (n, 1)

    def kernel(b_ref, nid_ref, s_ref, out_ref):
        r = pl.program_id(1)

        @pl.when(r == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        b = b_ref[...][:, 0].astype(jnp.int32)              # (blk,)
        nid = nid_ref[...][:, 0]                            # (blk,)
        s = s_ref[...]                                      # (blk, m)
        q = nid * n_bins + b                                # combined id
        oh = (q[:, None] == jnp.arange(Q)[None, :]).astype(jnp.float32)
        acc = jnp.dot(oh.T, s, preferred_element_type=jnp.float32)
        out_ref[...] += acc.reshape(1, n_nodes, n_bins, m)

    from ....kernels.runtime import interpret_mode
    out = pl.pallas_call(
        kernel,
        grid=(F, npad // blk),
        in_specs=[pl.BlockSpec((blk, 1), lambda f, r: (r, f)),
                  pl.BlockSpec((blk, 1), lambda f, r: (r, 0)),
                  pl.BlockSpec((blk, m), lambda f, r: (r, 0))],
        out_specs=pl.BlockSpec((1, n_nodes, n_bins, m),
                               lambda f, r: (f, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((F, n_nodes, n_bins, m), jnp.float32),
        interpret=interpret_mode(),
    )(binned, nid2, s32)
    return out.transpose(1, 0, 2, 3).astype(stats.dtype)


_PALLAS_PROBED: dict = {}      # (n_nodes, n_bins, m) -> bool (compiled ok)


def _pallas_probe(n_nodes: int, n_bins: int, m: int) -> bool:
    """EAGERLY compile+run the Pallas kernel at this level's shape class
    (tiny row count, one feature) before tracing it into the engine
    program. ``pl.pallas_call`` only stages the primitive at trace time —
    a Mosaic/interpreter failure would otherwise surface at
    ``queue.exec()``'s compile, OUTSIDE any try/except around the traced
    call — so the probe is what makes the demotion contract real for
    compile-time failures (VMEM overflow at deep levels, lane-alignment
    rejections), not just trace-time ones. One probe per shape class per
    process; probe failure demotes with the one-time warning."""
    key = (n_nodes, n_bins, m)
    ok = _PALLAS_PROBED.get(key)
    if ok is None:
        def probe():
            out = _pallas_level_hist(
                np.zeros((8, 1), np.int32), np.zeros((8, m), np.float32),
                np.zeros((8,), np.int32), n_nodes, n_bins)
            np.asarray(out)              # force the eager compile+run
        from ....kernels.runtime import demote_once, run_eagerly
        try:
            # run_eagerly (kernels/runtime.py): the dispatch call site
            # sits inside the engine's shard_map/jit trace, where even
            # concrete-input pallas_calls bind into the trace as
            # tracers; a fresh thread is a genuinely eager context, so
            # the probe really compiles+runs the kernel here and now.
            run_eagerly(probe)
            ok = True
        except Exception as e:  # pragma: no cover - backend-specific
            ok = False
            demote_once(
                "fused_hist", "probe-failed", gate=_PALLAS_WARNED,
                message=f"ALINK_TPU_FUSED_HIST=pallas failed to compile "
                        f"at level shape (n_nodes={n_nodes}, "
                        f"n_bins={n_bins}, m={m}) "
                        f"({type(e).__name__}: {e}); demoting to the "
                        f"fused XLA formulation")
        _PALLAS_PROBED[key] = ok
    return ok


def _hist_dispatch(hist_mode, pre, binned, stats, node_id, n_nodes, n_bins):
    """Per-level histogram under the resolved mode. Kept OUT of
    :func:`build_tree`'s flag-off path: with the flag off the original
    :func:`level_hist` call is executed verbatim (byte-identical HLO)."""
    if hist_mode == "pallas" and _pallas_probe(n_nodes, n_bins,
                                               stats.shape[1]):
        try:
            return _pallas_level_hist(binned, stats, node_id, n_nodes,
                                      n_bins)
        except Exception as e:  # pragma: no cover - backend-specific
            from ....kernels.runtime import demote_once
            demote_once(
                "fused_hist", "trace-failed", gate=_PALLAS_WARNED,
                message=f"ALINK_TPU_FUSED_HIST=pallas failed to trace "
                        f"({type(e).__name__}: {e}); demoting to the "
                        f"fused XLA formulation")
    return level_hist(binned, stats, node_id, n_nodes, n_bins,
                      use_onehot=True, pre=pre)


def _default_cat_order(hist):
    """Per-(node,feature,bin) ordering score for categorical subset splits:
    first-stat / count ratio — g/h-style mean response. Exact (Fisher) for
    regression and binary targets; a standard heuristic for multiclass.
    Empty bins sort last so unseen categories route right."""
    cnt = hist[..., -1]
    r = hist[..., 0] / jnp.maximum(cnt, 1e-12)
    return jnp.where(cnt > 0, r, jnp.inf)


def build_tree(binned, stats, max_depth: int, n_bins: int,
               gain_fn, leaf_fn, min_samples_leaf: float = 1.0,
               min_gain: float = 1e-9, feature_mask=None, axis_name=None,
               cat_feats=None, cat_order_fn=None, num_workers: int = 1):
    """Grow one tree; returns
    (features, split_bins, split_masks, leaf_values, node_id, leaf_hist,
     importance).

    binned: (n, F) int32; stats: (n, m) — zero rows are inert (padding /
    bagging handled by zeroing stats); feature_mask: (F,) 1/0 per-tree
    column subsample; axis_name: psum histograms across this mesh axis;
    cat_feats: (F,) bool — categorical features split on category
    *subsets* (bins sorted by ``cat_order_fn`` score, then cut like a
    threshold — the classical exact reduction, reference
    seriestree/CategoricalSplitter.java) instead of bin order.

    features/split_bins: (2^max_depth - 1,) level-order;
    split_masks: (2^max_depth - 1, n_bins) bool — per-node LEFT membership
    by bin (continuous nodes encode ``bin <= split_bin``), the single
    descent rule for both feature kinds; leaf_values: (2^max_depth, ...)
    from leaf_fn; node_id: (n,) final leaf; importance: (F,) summed split
    gain per feature (psum'd histograms make it identical on every worker).
    """
    n, F = binned.shape
    m = stats.shape[1]
    dt = stats.dtype
    node_id = jnp.zeros(n, jnp.int32)
    feats_out, bins_out, masks_out = [], [], []
    importance = jnp.zeros((F,), dt)
    cat_order_fn = cat_order_fn or _default_cat_order
    bins_ar = jnp.arange(n_bins)
    if cat_feats is not None:
        cat_np = np.asarray(cat_feats, bool)       # static column selection
        if not cat_np.any():
            cat_feats = None
        else:
            cat_idx = np.flatnonzero(cat_np)
            cat_pos = np.zeros(F, np.int32)        # F-index -> cat-slice index
            cat_pos[cat_idx] = np.arange(len(cat_idx), dtype=np.int32)
            cat_pos = jnp.asarray(cat_pos)
            cat_arr = jnp.asarray(cat_np)

    use_onehot = jax.default_backend() == "tpu"
    # ALINK_TPU_FUSED_HIST: resolved at trace time, folded into the
    # trainers' program-cache key. "off" executes the original
    # level_hist call verbatim (lowered HLO byte-identical to pre-flag
    # programs); the psum placement below is shared by every mode, so
    # the collective set never changes.
    hist_mode = fused_hist_mode()
    pre = (_fused_hist_precompute(binned, stats, n_bins)
           if hist_mode != "off" else None)
    for level in range(max_depth):
        n_nodes = 1 << level
        if hist_mode != "off":
            hist = _hist_dispatch(hist_mode, pre, binned, stats, node_id,
                                  n_nodes, n_bins)
        else:
            hist = level_hist(binned, stats, node_id, n_nodes, n_bins,
                              use_onehot)
        if axis_name is not None:
            # asarray materializes immediately: the per-level histogram
            # psums are dependency-ordered (level L's node assignment
            # needs level L-1's split), so there is nothing to fuse with
            hist = jnp.asarray(manifest_psum(hist, axis_name,
                                             name="tree_hist",
                                             num_workers=num_workers))
        cum = jnp.cumsum(hist, axis=2)
        total = cum[:, :, -1:, :]
        left = cum[:, :, :-1, :]                      # split "bin <= b"
        right = total - left
        gains = gain_fn(left, right, total, min_samples_leaf)  # (nodes,F,B-1)
        if cat_feats is not None:
            # sorted-by-score cumulation over ONLY the categorical columns
            # (static gather — continuous features skip the second pass):
            # cut position c sends the first c+1 bins (in score order) left
            hist_c = hist[:, cat_idx]                          # (nodes,Fc,B,m)
            total_c = total[:, cat_idx]
            order = jnp.argsort(cat_order_fn(hist_c), axis=2)  # (nodes,Fc,B)
            shist = jnp.take_along_axis(hist_c, order[..., None], 2)
            scum = jnp.cumsum(shist, axis=2)
            sleft = scum[:, :, :-1, :]
            sright = total_c - sleft
            sgains = gain_fn(sleft, sright, total_c, min_samples_leaf)
            gains = gains.at[:, cat_idx].set(sgains)
            # rank[bin] = position of bin in score order
            rank_c = jnp.argsort(order, axis=2)                # (nodes,Fc,B)
        if feature_mask is not None:
            gains = jnp.where(feature_mask[None, :, None] > 0, gains, -jnp.inf)
        flat_g = gains.reshape(n_nodes, F * (n_bins - 1))
        best = jnp.argmax(flat_g, axis=1)
        best_gain = jnp.take_along_axis(flat_g, best[:, None], 1)[:, 0]
        best_f = (best // (n_bins - 1)).astype(jnp.int32)
        best_b = (best % (n_bins - 1)).astype(jnp.int32)
        split = best_gain > min_gain
        feats_out.append(jnp.where(split, best_f, -1))
        bins_out.append(jnp.where(split, best_b, 0))
        # LEFT-membership mask per node over bins
        if cat_feats is not None:
            brank = jnp.take_along_axis(
                rank_c, cat_pos[best_f][:, None, None], 1)[:, 0, :]  # (nodes,B)
            is_cat = cat_arr[best_f]
            pos = jnp.where(is_cat[:, None], brank, bins_ar[None, :])
        else:
            pos = jnp.broadcast_to(bins_ar[None, :], (n_nodes, n_bins))
        mask = pos <= best_b[:, None]                          # (nodes, B)
        masks_out.append(mask & split[:, None])
        importance = importance.at[best_f].add(
            jnp.where(split, best_gain, jnp.zeros_like(best_gain)))
        # descend: right iff split and sample's bin is not in the left set
        nf = feats_out[-1][node_id]
        sample_bin = jnp.take_along_axis(binned, jnp.maximum(nf, 0)[:, None], 1)[:, 0]
        in_left = masks_out[-1][node_id, sample_bin]
        go_right = (nf >= 0) & jnp.logical_not(in_left)
        node_id = node_id * 2 + go_right.astype(jnp.int32)

    n_leaves = 1 << max_depth
    leaf_hist = jnp.zeros((n_leaves, m), dt).at[node_id].add(stats)
    if axis_name is not None:
        leaf_hist = jnp.asarray(manifest_psum(leaf_hist, axis_name,
                                              name="tree_leaf_hist",
                                              num_workers=num_workers))
    features = jnp.concatenate(feats_out)
    split_bins = jnp.concatenate(bins_out)
    split_masks = jnp.concatenate(masks_out, axis=0)
    return (features, split_bins, split_masks, leaf_fn(leaf_hist), node_id,
            leaf_hist, importance)


def tree_apply_binned(binned, features, split_bins, max_depth: int,
                      split_masks=None):
    """Final leaf index for each row, descending the dense tree (traceable).

    With ``split_masks`` (n_internal, n_bins) the descent uses the uniform
    LEFT-membership rule (required for categorical splits; identical to
    ``bin <= split_bin`` for continuous nodes)."""
    n = binned.shape[0]
    node = jnp.zeros(n, jnp.int32)
    offset = 0
    for level in range(max_depth):
        gi = offset + node
        f = features[gi]
        sample_bin = jnp.take_along_axis(binned, jnp.maximum(f, 0)[:, None], 1)[:, 0]
        if split_masks is not None:
            in_left = split_masks[gi, sample_bin]
            go_right = (f >= 0) & jnp.logical_not(in_left)
        else:
            go_right = (f >= 0) & (sample_bin > split_bins[gi])
        node = node * 2 + go_right.astype(jnp.int32)
        offset += 1 << level
    return node


def bins_to_thresholds(features: np.ndarray, split_bins: np.ndarray,
                       edges: np.ndarray) -> np.ndarray:
    """Real-valued split thresholds for host-side serving: x > thr -> right."""
    thr = np.zeros(features.shape, np.float64)
    for i, (f, b) in enumerate(zip(features, split_bins)):
        thr[i] = edges[int(f), int(b)] if f >= 0 else 0.0
    return thr


def tree_apply_values(X: np.ndarray, features: np.ndarray, thresholds: np.ndarray,
                      max_depth: int, cat_mask: Optional[np.ndarray] = None,
                      split_masks: Optional[np.ndarray] = None) -> np.ndarray:
    """Host/numpy descent on raw feature values.

    Categorical nodes (``cat_mask[f]``) route by LEFT-membership of the
    category code in ``split_masks[node]``; out-of-vocabulary codes route
    right (never in the left set)."""
    n = X.shape[0]
    node = np.zeros(n, np.int64)
    offset = 0
    n_bins = split_masks.shape[1] if split_masks is not None else 0
    for level in range(max_depth):
        gi = offset + node
        f = features[gi].astype(np.int64)
        thr = thresholds[gi]
        x = X[np.arange(n), np.maximum(f, 0)]
        go_right = (f >= 0) & (x > thr)
        if cat_mask is not None and split_masks is not None:
            code = np.round(x).astype(np.int64)
            in_left = np.where(
                code >= 0,
                split_masks[gi, np.clip(code, 0, n_bins - 1)], False)
            is_cat = cat_mask[np.maximum(f, 0)] & (f >= 0)
            go_right = np.where(is_cat, (f >= 0) & ~in_left, go_right)
        node = node * 2 + go_right
        offset += 1 << level
    return node
