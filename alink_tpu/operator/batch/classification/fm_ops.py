"""FM classifier/regressor batch operators.

Re-design of batch/classification/FmClassifierTrainBatchOp and
batch/regression/FmRegressorTrainBatchOp (+ predict ops) over common/fm.
"""

from __future__ import annotations

import json
from typing import List, Optional

import numpy as np

from ....common.mtable import MTable
from ....common.params import ParamInfo, Params, RangeValidator
from ....common.types import AlinkTypes, TableSchema
from ....mapper.base import ModelMapper, OutputColsHelper
from ....model.converters import (SimpleModelDataConverter, decode_array,
                                  encode_array)
from ....params.shared import (HasFeatureCols, HasLabelCol, HasPredictionCol,
                               HasPredictionDetailCol, HasReservedCols, HasSeed,
                               HasVectorCol, HasWeightCol)
from ...base import BatchOperator
from ...common.dataproc.feature_extract import extract_design, resolve_feature_cols
from ...common.fm.fm import FmTrainParams, fm_predict_margin, fm_train
from ...common.linear.base import encode_labels
from ..utils.model_map import ModelMapBatchOp


class FmModelData:
    def __init__(self, w0, w, V, is_regression, vector_col, feature_cols,
                 label_values, label_type=AlinkTypes.STRING):
        self.w0, self.w, self.V = w0, w, V
        self.is_regression = is_regression
        self.vector_col = vector_col
        self.feature_cols = feature_cols
        self.label_values = label_values
        self.label_type = label_type


class FmModelDataConverter(SimpleModelDataConverter):
    """reference: common/fm/FmModelDataConverter.java"""

    def serialize_model(self, m: FmModelData):
        meta = Params({"is_regression": m.is_regression, "vector_col": m.vector_col,
                       "feature_cols": m.feature_cols,
                       "label_values": [str(v) for v in (m.label_values or [])],
                       "label_type": m.label_type,
                       "raw_labels": json.dumps(m.label_values, default=str)})
        return meta, [encode_array(np.asarray([m.w0])), encode_array(m.w),
                      encode_array(m.V)]

    def deserialize_model(self, meta, data):
        labels = meta._m.get("label_values") or []
        lt = meta._m.get("label_type", AlinkTypes.STRING)
        if lt in (AlinkTypes.LONG, AlinkTypes.INT):
            labels = [int(float(v)) for v in labels]
        elif lt in (AlinkTypes.DOUBLE, AlinkTypes.FLOAT):
            labels = [float(v) for v in labels]
        return FmModelData(
            float(decode_array(data[0])[0]), decode_array(data[1]),
            decode_array(data[2]), bool(meta._m.get("is_regression")),
            meta._m.get("vector_col"), meta._m.get("feature_cols"), labels, lt)


class _FmTrainParamsMixin(HasLabelCol, HasFeatureCols, HasVectorCol, HasWeightCol,
                          HasSeed):
    NUM_FACTOR = ParamInfo("num_factor", int, "latent factors", default=10,
                           validator=RangeValidator(1, None))
    NUM_EPOCHS = ParamInfo("num_epochs", int, default=10,
                           validator=RangeValidator(1, None))
    LEARN_RATE = ParamInfo("learn_rate", float, default=0.05)
    INIT_STDEV = ParamInfo("init_stdev", float, default=0.05)
    LAMBDA_0 = ParamInfo("lambda_0", float, default=0.0)
    LAMBDA_1 = ParamInfo("lambda_1", float, default=0.0)
    LAMBDA_2 = ParamInfo("lambda_2", float, default=0.0)
    WITH_INTERCEPT = ParamInfo("with_intercept", bool, default=True)
    WITH_LINEAR_ITEM = ParamInfo("with_linear_item", bool, default=True)


class BaseFmTrainBatchOp(BatchOperator, _FmTrainParamsMixin):
    IS_REGRESSION = False

    def link_from(self, in_op: BatchOperator):
        import jax
        t = in_op.get_output_table()
        dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
        vector_col = self.params._m.get("vector_col")
        feature_cols = self.params._m.get("feature_cols")
        label_col = self.get_label_col()
        weight_col = self.params._m.get("weight_col")
        if not vector_col:
            feature_cols = resolve_feature_cols(
                t, feature_cols, label_col,
                exclude=[weight_col] if weight_col else [])
        design = extract_design(t, feature_cols, vector_col, dtype)
        raw = t.col(label_col)
        label_type = t.schema.type_of(label_col)
        if self.IS_REGRESSION:
            labels, y = [], np.asarray(raw, dtype)
        else:
            labels, y = encode_labels(
                raw, self.params._m.get("positive_label_value_string"))
        w = (np.asarray(t.col(weight_col), dtype) if weight_col
             else np.ones(t.num_rows, dtype))
        data = {k: v for k, v in design.items() if k in ("X", "idx", "val")}
        data["y"] = y.astype(dtype)
        data["w"] = w
        p = FmTrainParams(
            num_factors=self.get_num_factor(), learn_rate=self.get_learn_rate(),
            init_stdev=self.get_init_stdev(), num_epochs=self.get_num_epochs(),
            lambda_0=self.get_lambda_0(), lambda_1=self.get_lambda_1(),
            lambda_2=self.get_lambda_2(), with_intercept=self.get_with_intercept(),
            with_linear_item=self.get_with_linear_item(),
            is_regression=self.IS_REGRESSION, seed=self.get_seed())
        w0, wv, V, curve, steps = fm_train(data, design["dim"], p)
        model = FmModelData(w0, wv, V, self.IS_REGRESSION, vector_col,
                            feature_cols, labels, label_type)
        self._output = FmModelDataConverter().save_model(model)
        self._side_outputs = [MTable({"epoch": np.arange(1, len(curve) + 1),
                                      "loss": curve.astype(np.float64)})]
        return self

    def get_model_info(self) -> MTable:
        m = FmModelDataConverter().load_model(self.get_output_table())
        return FmModelInfo(m).to_table()


class FmClassifierTrainBatchOp(BaseFmTrainBatchOp):
    IS_REGRESSION = False


class FmRegressorTrainBatchOp(BaseFmTrainBatchOp):
    IS_REGRESSION = True


class FmModelInfo:
    """FM model summary (reference common/fm/FmModelInfo.java:18-58): task,
    latent dimension, vector size, factor matrix, feature columns."""

    def __init__(self, m: FmModelData):
        self._m = m

    def get_task(self) -> str:
        return "REGRESSION" if self._m.is_regression else "BINARY_CLASSIFICATION"

    def get_num_factor(self) -> int:
        return int(self._m.V.shape[1])

    def get_vector_size(self) -> int:
        return int(self._m.w.shape[0])

    def get_factors(self) -> np.ndarray:
        return np.asarray(self._m.V)

    def get_col_names(self):
        return self._m.feature_cols

    def to_table(self) -> MTable:
        m = self._m
        V = np.asarray(m.V)
        return MTable({
            "task": [self.get_task()],
            "vector_size": [self.get_vector_size()],
            "num_factor": [self.get_num_factor()],
            "intercept": [float(m.w0)],
            "linear_norm": [float(np.linalg.norm(np.asarray(m.w)))],
            "factor_norm": [float(np.linalg.norm(V))],
            "feature_cols": [",".join(m.feature_cols or [])
                             if m.feature_cols else (m.vector_col or "")],
        })

    def __repr__(self):
        return (f"FmModelInfo(task={self.get_task()}, "
                f"vector_size={self.get_vector_size()}, "
                f"num_factor={self.get_num_factor()})")


class FmModelInfoBatchOp(BatchOperator):
    """Link to the output of an FM trainer to summarize the model
    (reference operator/common/fm/FmModelInfoBatchOp.java:15-40, built on
    ExtractModelInfoBatchOp). ``collect_model_info()`` returns the
    FmModelInfo; the op's output table is the one-row summary."""

    def link_from(self, in_op: BatchOperator) -> "FmModelInfoBatchOp":
        model = FmModelDataConverter().load_model(in_op.get_output_table())
        self._info = FmModelInfo(model)
        self._output = self._info.to_table()
        return self

    def collect_model_info(self) -> FmModelInfo:
        return self._info

    def lazy_print_model_info(self, title=None) -> "FmModelInfoBatchOp":
        def show(t: MTable):
            if title:
                print(title)
            print(t.to_display_string())
        return self._lazy("model_info", self.get_output_table(), show)


class FmModelMapper(ModelMapper):
    """reference: common/fm/FmModelMapper.java"""

    def __init__(self, model_schema, data_schema, params=None, **kwargs):
        super().__init__(model_schema, data_schema, params, **kwargs)
        self.model: Optional[FmModelData] = None

    def load_model(self, model_table: MTable):
        self.model = FmModelDataConverter().load_model(model_table)

    def get_output_schema(self) -> TableSchema:
        """Output schema without running the mapper — required by the
        stream predict twins (`ModelMapStreamOp._open`); the batch path
        never calls it, which is why the FM twin could not open."""
        m = self.model
        return self._pred_output_schema(
            m.label_type if m else AlinkTypes.STRING,
            bool(m is not None and m.is_regression))

    def map_table(self, data: MTable) -> MTable:
        m = self.model
        design = extract_design(data, m.feature_cols, m.vector_col, np.float64,
                                vector_size=m.w.shape[0])
        return self._finish(fm_predict_margin(m.w0, m.w, m.V, design), data)

    def serving_kernel(self):
        """Compiled-serving contract (serving/predictor.py) for FM: the
        margin ``w0 + <w,x> + 1/2 sum_f((Vx)_f^2 - (V^2 x^2)_f)`` with
        every feature/factor reduction a strict left-to-right
        ``lax.scan`` over materialized terms (serving/sharded.py
        ``scan_sum``) so the rounding cannot depend on the shape bucket —
        padding is a bitwise no-op. Against the numpy mapper (BLAS
        reduction order) labels are exact and margins match to ~1e-12
        relative; weights (w0, w, V) are program ARGUMENTS, so
        hot-swapped same-geometry FM models compile nothing."""
        m = self.model
        if m is None:
            raise RuntimeError(
                "load_model must be called before serving_kernel")
        import jax

        from ....serving.predictor import ServingKernel
        from ....serving.sharded import SERVE_CHUNK
        ship_dt = np.float64 if jax.config.jax_enable_x64 else np.float32
        dim = int(m.w.shape[0])
        k = int(m.V.shape[1])
        dim8 = -(-dim // SERVE_CHUNK) * SERVE_CHUNK
        w = np.zeros(dim8, ship_dt)
        w[:dim] = np.asarray(m.w, ship_dt)
        V = np.zeros((dim8, k), ship_dt)
        V[:dim] = np.asarray(m.V, ship_dt)
        model_arrays = (np.asarray(m.w0, ship_dt), w, V)
        signature = ("fm", bool(m.is_regression), dim, k,
                     str(ship_dt.__name__))

        def encode(data: MTable, bucket: int):
            design = extract_design(data, m.feature_cols, m.vector_col,
                                    ship_dt, vector_size=dim)
            n = data.num_rows
            if design["kind"] == "dense":
                Xf = design["X"]
                X = np.zeros((bucket, dim8), ship_dt)
                X[:n, :Xf.shape[1]] = Xf
                return ("dense", (X,))
            idx0, val0 = design["idx"], design["val"]
            w0 = max(idx0.shape[1], 1)
            width = -(-w0 // SERVE_CHUNK) * SERVE_CHUNK
            idx = np.zeros((bucket, width), np.int32)
            val = np.zeros((bucket, width), ship_dt)
            idx[:n, :idx0.shape[1]] = idx0
            val[:n, :val0.shape[1]] = val0
            return ("sparse", (idx, val))

        def _dense(mdl, X):
            from ....serving.sharded import scan_sum
            w0_, w_, V_ = mdl
            lin = scan_sum(X * w_[None, :], axis=1)
            s = scan_sum(X[:, :, None] * V_[None, :, :], axis=1)
            sq = scan_sum((X * X)[:, :, None] * (V_ * V_)[None, :, :],
                          axis=1)
            return w0_ + lin + 0.5 * scan_sum(s * s - sq, axis=1)

        def _sparse(mdl, idx, val):
            from ....serving.sharded import scan_sum
            w0_, w_, V_ = mdl
            lin = scan_sum(val * w_[idx], axis=1)
            s = scan_sum(val[..., None] * V_[idx], axis=1)
            sq = scan_sum((val * val)[..., None] * (V_ * V_)[idx],
                          axis=1)
            return w0_ + lin + 0.5 * scan_sum(s * s - sq, axis=1)

        def decode(outputs, data: MTable) -> MTable:
            return self._finish(np.asarray(outputs[0], np.float64), data)

        return ServingKernel(signature=signature, model_arrays=model_arrays,
                             encode=encode,
                             device_fns={"dense": _dense,
                                         "sparse": _sparse},
                             decode=decode)

    def _finish(self, margin: np.ndarray, data: MTable) -> MTable:
        """Margins -> output table (label pick, detail, column merge) —
        split out of :meth:`map_table` so the serving tier decodes
        DEVICE-computed margins through the exact same host logic."""
        m = self.model
        pred_col = self.params._m.get("prediction_col", "pred")
        detail_col = self.params._m.get("prediction_detail_col")
        reserved = self.params._m.get("reserved_cols")
        if m.is_regression:
            cols, types, vals = [pred_col], [AlinkTypes.DOUBLE], [margin]
        else:
            p_pos = 1.0 / (1.0 + np.exp(-np.clip(margin, -500, 500)))
            preds = np.empty(len(margin), object)
            preds[:] = [m.label_values[0] if s > 0 else m.label_values[1]
                        for s in margin]
            cols, types, vals = [pred_col], [m.label_type], [preds]
            if detail_col:
                details = np.asarray(
                    [json.dumps({str(m.label_values[0]): float(p),
                                 str(m.label_values[1]): float(1 - p)})
                     for p in p_pos], object)
                cols.append(detail_col)
                types.append(AlinkTypes.STRING)
                vals.append(details)
        helper = OutputColsHelper(data.schema, cols, types, reserved)
        return helper.build_output(data, vals)


class FmPredictBatchOp(ModelMapBatchOp, HasPredictionCol, HasPredictionDetailCol,
                       HasReservedCols):
    MAPPER_CLS = FmModelMapper


FmClassifierPredictBatchOp = FmPredictBatchOp
FmRegressorPredictBatchOp = FmPredictBatchOp
